"""PieceManager: moves one piece (or a whole file) into storage.

Role parity: reference ``client/daemon/peer/piece_manager.go`` —
``DownloadPiece`` (:170, P2P fetch from a parent with digest verify),
``DownloadSource`` (:303, whole-file back-source incl. unknown length),
``concurrentDownloadSourceByPieceGroup`` (:815, origin range split across
workers). P2P piece fetch itself lives in ``piece_downloader.py``.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from typing import TYPE_CHECKING

from ..common import digest as digestlib
from ..common.errors import Code, DFError
from ..common.piece import (INGEST_DMA_UNIT_BYTES, Range, compute_piece_size,
                            piece_count, piece_range)
from ..common.rate import TokenBucket
from ..common.retry import Retrier, RetryPolicy
from ..idl.messages import PieceInfo
from ..source import SourceRequest, client_for
from ..source import download as source_download
from .config import DownloadConfig

if TYPE_CHECKING:  # pragma: no cover
    from .conductor import PeerTaskConductor

log = logging.getLogger("df.core.piece")

# back-to-source fetch ladder: transient origin failures (5xx, transport)
# retry under ONE policy, honoring the origin's Retry-After hint when it
# sent one; NOT_FOUND/AUTH are verdicts, not weather, and fail immediately
_SOURCE_RETRY = RetryPolicy(max_attempts=3, base_s=0.5, max_s=8.0,
                            budget_s=60.0)


def _transient_source(exc: BaseException) -> bool:
    return (isinstance(exc, DFError)
            and exc.code in (Code.SOURCE_ERROR, Code.UNAVAILABLE,
                             Code.DEADLINE_EXCEEDED))


async def _open_source(req: SourceRequest):
    """Open an origin stream with the unified retry/backoff policy. Only
    the OPEN retries here: pieces already landed from a stream that died
    midway are deduped at landing, so callers that restart a whole group
    stay correct without double-counting."""
    return await Retrier(_SOURCE_RETRY).run(
        lambda: source_download(req), retryable=_transient_source)


def _relay_for(conductor):
    """The relay hub when the conductor registered with it — origin bytes
    then serve onward while the piece is still arriving (the seed hop of
    a cut-through chain, daemon/relay.py)."""
    if getattr(conductor, "_relay_tracked", False):
        return conductor.relay
    return None


class _PieceCutter:
    """Cuts an origin byte stream into per-piece buffers, each registered
    as an in-flight relay span while it fills (one buffer per piece, not
    one rolling bytearray, so the span's watermark maps 1:1 onto the
    landing buffer). Shared by the single-stream and piece-group
    back-source paths — the span lifecycle (open → advance → land →
    retire, retire-on-death in ``close``) lives in exactly one place.

    ``want(num, rel)`` returns the next piece's size; <= 0 stops
    consuming (origin over-delivery, or the group bound). Spans carry no
    digest (none is known until landing) — a child landing a relayed
    origin piece gets the same trust it would fetching the origin
    itself."""

    def __init__(self, conductor, *, start_num: int, start_rel: int, want):
        self.conductor = conductor
        self.relay = _relay_for(conductor)
        self.want = want
        self.num = start_num
        self.rel = start_rel
        self.cur: bytearray | None = None
        self.span = None
        self.filled = 0
        self.t0 = time.monotonic()

    async def feed(self, chunk) -> None:
        coff = 0
        while coff < len(chunk):
            if self.cur is None:
                want = self.want(self.num, self.rel)
                if want <= 0:
                    return
                self.cur = bytearray(want)
                self.filled = 0
                if self.relay is not None:
                    self.span = self.relay.open_span(
                        self.conductor.task_id, self.rel, want, self.cur,
                        [PieceInfo(piece_num=self.num,
                                   range_start=self.rel,
                                   range_size=want)])
            take = min(len(self.cur) - self.filled, len(chunk) - coff)
            self.cur[self.filled:self.filled + take] = \
                chunk[coff:coff + take]
            self.filled += take
            coff += take
            if self.span is not None:
                self.span.advance(self.filled)
            if self.filled == len(self.cur):
                await self._land(bytes(self.cur))
                self.cur = None

    async def _land(self, data: bytes) -> None:
        cost = int((time.monotonic() - self.t0) * 1000)
        await self.conductor.on_piece_from_source(self.num, self.rel,
                                                  data, cost)
        if self.relay is not None:
            self.relay.retire(self.span)   # landed: serves from disk
        self.span = None
        self.num += 1
        self.rel += len(data)
        self.t0 = time.monotonic()

    async def flush_tail(self) -> None:
        """Origin ended short of the expected piece size: land what came
        (single-stream semantics; group streams treat short as an error)."""
        if self.cur is not None and self.filled:
            await self._land(bytes(self.cur[:self.filled]))
            self.cur = None

    def close(self) -> None:
        """Stream died mid-piece: retire the leftover span."""
        if self.relay is not None and self.span is not None:
            self.relay.retire(self.span)
            self.span = None


class PieceManager:
    def __init__(self, cfg: DownloadConfig):
        self.cfg = cfg
        self.total_limiter = TokenBucket(cfg.total_rate_limit_bps or 0)

    def _limiter(self, conductor) -> TokenBucket:
        # the shaper's per-task bucket when attached; daemon-wide otherwise
        return getattr(conductor, "rate_limiter", None) or self.total_limiter

    # ------------------------------------------------------------------
    # back-source: origin -> storage
    # ------------------------------------------------------------------

    async def download_source(self, conductor: "PeerTaskConductor") -> None:
        """Fetch the conductor's full content (or sub-range) from the origin."""
        from ..common.piece import parse_http_range

        client = client_for(conductor.url)
        header = dict(conductor.url_meta.header or {})
        probe = SourceRequest(url=conductor.url, header=header)
        total = await client.content_length(probe)
        ranged = await client.supports_range(probe)

        # resolve a requested sub-range against the real total: the conductor
        # then stores ONLY the range, at range-relative offsets
        if conductor.url_meta.range and conductor.content_range is None:
            if not ranged:
                raise DFError(Code.SOURCE_RANGE_UNSUPPORTED,
                              "origin cannot serve the requested range")
            limit = total if total >= 0 else (1 << 62)
            try:
                conductor.content_range = parse_http_range(
                    conductor.url_meta.range, limit)
            except ValueError as exc:
                raise DFError(Code.INVALID_ARGUMENT, str(exc)) from None
        req = SourceRequest(url=conductor.url, header=header,
                            range=conductor.content_range)
        effective = (conductor.content_range.length
                     if conductor.content_range is not None else total)

        if effective < 0:
            await self._download_unknown_length(conductor, req)
            return

        piece_size = conductor.set_content_info(effective)
        n = piece_count(effective, piece_size)
        # warm adoption BEFORE any origin byte moves: pieces this task
        # already holds on disk (surviving storage from a restart, or a
        # retry over an earlier attempt) land as content-store placements,
        # and the origin is only asked for the holes
        if conductor.storage is not None and conductor.storage.md.pieces:
            await conductor.place_from_store(
                [m.to_info() for m in
                 list(conductor.storage.md.pieces.values())])
        # the hole universe is the NEEDED pieces: a sharded task's
        # requested-shard subset asks the origin for only the ranges that
        # cover its shards (the missing-run range groups skip the rest).
        # Looped: a joiner may WIDEN the needed set mid-fetch
        # (conductor.widen_to_whole_file) — re-deriving the holes after
        # each round fetches the newly-needed ranges instead of
        # finishing a now-stale subset, and the commit flag is set in
        # the same synchronous block as the final emptiness check so a
        # widen can never slip between "covered" and finalize.
        prev_missing: list[int] | None = None
        while True:
            missing = [i for i in conductor.needed_piece_nums(n)
                       if i not in conductor.ready]
            if not missing:
                conductor._finishing = True
                break
            if missing == prev_missing:
                # a round moved nothing: surface it instead of spinning
                raise DFError(Code.SOURCE_ERROR,
                              f"origin round landed none of "
                              f"{len(missing)} missing pieces")
            prev_missing = missing
            partial = len(missing) < n
            if (ranged and self.cfg.back_source_parallelism > 1
                    and (partial
                         or effective
                         >= self.cfg.back_source_group_min_bytes)):
                # the piece-group path also serves the hole-filling case:
                # its range reads skip everything already on disk
                await self._download_piece_groups(conductor, req,
                                                  effective, piece_size,
                                                  missing)
            else:
                await self._download_stream(conductor, req, piece_size,
                                            start_piece=0)
        conductor.on_source_complete(effective)

    async def _download_stream(self, conductor, req: SourceRequest,
                               piece_size: int, start_piece: int) -> None:
        """One origin stream, cut into pieces as bytes arrive — each
        in-progress piece is an in-flight relay span (``_PieceCutter``):
        children may pull it from this daemon's upload server up to the
        watermark while the origin is still delivering it."""
        resp = await _open_source(req)
        total = conductor.content_length
        assert resp.chunks is not None
        limiter = self._limiter(conductor)
        # offsets are range-relative: the task stores just its range
        cutter = _PieceCutter(
            conductor, start_num=start_piece, start_rel=0,
            want=lambda _num, rel: (piece_size if total < 0
                                    else min(piece_size, total - rel)))
        try:
            async for chunk in resp.chunks:
                await limiter.acquire(len(chunk))
                await cutter.feed(chunk)
            # origin ended short of the expected size: land what came
            await cutter.flush_tail()
        finally:
            cutter.close()   # stream died mid-piece

    async def _download_piece_groups(self, conductor, req: SourceRequest,
                                     total: int, piece_size: int,
                                     missing: list[int] | int) -> None:
        """Work-queue of contiguous piece groups over the MISSING pieces:
        each worker streams the next unclaimed group (parallel GCS/HTTP
        range reads). A warm task's already-held pieces split the runs, so
        the origin only ever serves the holes.

        Dynamic claiming instead of a static per-worker partition does two
        things: a faster origin stream takes more groups (no straggler owns
        a fixed quarter), and coverage advances front-to-back, so
        DeviceIngest shards complete progressively and their host->HBM
        transfers overlap the download — with static quarters every worker
        finished at once and every DMA fired after the last byte (the r04
        bench measured 0% ingest overlap that way)."""
        if isinstance(missing, int):     # piece count: nothing held yet
            missing = list(range(missing))
        m = len(missing)
        workers = min(self.cfg.back_source_parallelism, m)
        # one DMA unit per group: big enough that per-request origin overhead
        # is noise, small enough that groups never span ingest shards. The
        # tail stretch (last ~2 rounds of the worker pool) halves the group
        # size: with N groups ~= N workers every stream finishes together
        # and the final ingest shards all ship after the last byte — smaller
        # tail groups stagger the finishes so the tail DMA overlaps too.
        group_pieces = max(1, min(INGEST_DMA_UNIT_BYTES // piece_size,
                                  -(-m // workers)))
        bounds: list[tuple[int, int]] = []
        idx = 0
        while idx < m:
            size = group_pieces
            if m - idx <= 2 * workers * group_pieces and group_pieces > 1:
                size = max(1, group_pieces // 2)
            # clip the group to the contiguous run starting here: a group
            # must be one origin Range, and held pieces break the run
            end = idx + 1
            while end < min(idx + size, m) \
                    and missing[end] == missing[end - 1] + 1:
                end += 1
            bounds.append((missing[idx], missing[end - 1] + 1))
            idx = end
        queue = collections.deque(bounds)
        base = req.range.start if req.range else 0
        content_len = req.range.length if req.range else total

        async def group(first: int, last: int) -> None:
            g_off, _ = piece_range(first, piece_size, content_len)
            g_end_off, g_end_len = piece_range(last - 1, piece_size, content_len)
            g_range = Range(base + g_off, g_end_off + g_end_len - g_off)
            sub = SourceRequest(url=req.url, header=dict(req.header),
                               range=g_range, timeout_s=req.timeout_s)
            resp = await _open_source(sub)
            assert resp.chunks is not None
            limiter = self._limiter(conductor)
            # per-piece buffer + relay span, like _download_stream: each
            # in-progress piece of every group is cut-through servable
            cutter = _PieceCutter(
                conductor, start_num=first, start_rel=g_off,
                want=lambda num, _rel: (piece_range(num, piece_size,
                                                    content_len)[1]
                                        if num < last else 0))
            try:
                async for chunk in resp.chunks:
                    await limiter.acquire(len(chunk))
                    await cutter.feed(chunk)
            finally:
                cutter.close()   # group stream died mid-piece
            if cutter.num != last:
                raise DFError(Code.CLIENT_BACK_SOURCE_ERROR,
                              f"short origin range read: group stopped at "
                              f"piece {cutter.num}/{last}")

        async def worker() -> None:
            while queue:
                first, last = queue.popleft()
                await group(first, last)

        results = await asyncio.gather(*(worker() for _ in range(workers)),
                                       return_exceptions=True)
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            raise errs[0]

    async def _download_unknown_length(self, conductor,
                                       req: SourceRequest) -> None:
        """Origin without Content-Length: stream until EOF, sizes learned at
        the end (reference ``downloadUnknownLengthSource``)."""
        piece_size = conductor.set_content_info(-1)
        resp = await _open_source(req)
        num = 0
        off = 0
        buf = bytearray()
        t0 = time.monotonic()
        assert resp.chunks is not None
        limiter = self._limiter(conductor)
        async for chunk in resp.chunks:
            await limiter.acquire(len(chunk))
            buf.extend(chunk)
            while len(buf) >= piece_size:
                data = bytes(buf[:piece_size])
                del buf[:piece_size]
                cost = int((time.monotonic() - t0) * 1000)
                await conductor.on_piece_from_source(num, off, data, cost)
                num += 1
                off += len(data)
                t0 = time.monotonic()
        if buf:
            await conductor.on_piece_from_source(
                num, off, bytes(buf), int((time.monotonic() - t0) * 1000))
            off += len(buf)
        conductor.on_source_complete(off)

    # ------------------------------------------------------------------
    # import: local file -> storage (dfcache)
    # ------------------------------------------------------------------

    async def import_file(self, conductor: "PeerTaskConductor", path: str) -> None:
        import os

        # dfcache import can be GBs: the per-piece reads go through the
        # DEFAULT executor, not the 4-thread storage pool — a multi-GB
        # import queued on the pool would park every in-flight span
        # landing behind it (same rationale as conductor._verify_digest).
        loop = asyncio.get_running_loop()
        total = await loop.run_in_executor(None, os.path.getsize, path)
        piece_size = conductor.set_content_info(total)
        f = await loop.run_in_executor(None, lambda: open(path, "rb"))
        try:
            num, off = 0, 0
            while True:
                data = await loop.run_in_executor(None, f.read, piece_size)
                if not data:
                    break
                await conductor.on_piece_from_source(num, off, data, 0)
                num += 1
                off += len(data)
        finally:
            f.close()
        conductor.on_source_complete(total)


def verify_content_digest(expected: str, algo_stream) -> None:
    """Raise CLIENT_DIGEST_MISMATCH unless the streamed hash matches."""
    algo, want = digestlib.parse(expected)
    got = digestlib.hash_stream(algo, algo_stream)
    if got != want:
        raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                      f"content digest mismatch: want {algo}:{want[:16]}.., "
                      f"got {algo}:{got[:16]}..")
