"""Upload server: the HTTP surface other peers fetch pieces from.

Role parity: reference ``client/daemon/upload/upload_manager.go`` — route
``GET /download/{taskID[:3]}/{taskID}?peerId=`` with a ``Range:`` header,
served straight from the piece store, rate-limited, instrumented.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque

from aiohttp import web

from ..common import faultgate, tracing
from ..common.aiohttp_util import resolve_port
from ..common.errors import DFError
from ..common.metrics import BYTES_BUCKETS, REGISTRY
from ..common.piece import parse_http_range
from ..common.rate import TokenBucket
from ..storage.io_executor import run_io
from ..storage.manager import StorageManager

log = logging.getLogger("df.http.upload")

_upload_bytes = REGISTRY.counter("df_upload_bytes_total",
                                 "bytes served to other peers")
_upload_reqs = REGISTRY.counter("df_upload_requests_total",
                                "piece requests served", ("status",))
_upload_active = REGISTRY.gauge("df_upload_active_transfers",
                                "concurrency-gate slots currently held")
_upload_piece_bytes = REGISTRY.histogram(
    "df_upload_transfer_bytes", "size of each piece/span transfer served",
    buckets=BYTES_BUCKETS)
# serve-side edge accounting (podscope): how long each served range held
# its upload slot (limiter wait + storage read + body transmit), and the
# limiter-wait share — the parent-side numbers that say whether a slow
# edge was the parent's uplink or the child's intake
_upload_serve_secs = REGISTRY.histogram(
    "df_upload_serve_seconds",
    "upload-slot hold time per served range (wait + read + transmit)")
_upload_wait_secs = REGISTRY.histogram(
    "df_upload_limiter_wait_seconds",
    "rate-limiter wait per served range")
# cut-through relay serving (daemon/relay.py): ranges streamed against the
# landing watermark instead of 416ing on an incomplete piece
_relay_serves = REGISTRY.counter(
    "df_relay_serves_total",
    "streaming relay range serves", ("result",))
_relay_bytes = REGISTRY.counter(
    "df_relay_bytes_total",
    "bytes served by the streaming relay path", ("src",))
_relay_stalls = REGISTRY.counter(
    "df_relay_stalls_total",
    "relay serves aborted because the landing watermark stopped advancing")
_relay_wait_secs = REGISTRY.histogram(
    "df_relay_wait_seconds",
    "time a streaming relay serve spent awaiting landing progress")
# class-aware upload admission (multi-tenant QoS): bulk-class piece GETs
# are capped below the total concurrency gate so a bulk herd can never
# occupy every slot a critical child needs
_qos_upload_active = REGISTRY.gauge(
    "df_qos_upload_active", "upload slots currently held, by requesting "
    "class", ("cls",))
_qos_upload_shed = REGISTRY.counter(
    "df_qos_upload_shed_total",
    "piece requests 503-shed at the class-aware upload gate", ("cls",))


class _Slot:
    """One concurrency-gate slot, held until the response BODY is fully
    written (or the connection dies) — not merely until the handler
    returns. aiohttp sends FileResponse/Response bodies after the handler
    frame exits, so decrementing there would gate nothing on the transfer
    path (the round-3 defect: with rate_limit_bps=0 the slot was held for
    microseconds and the 503 backpressure never engaged)."""

    __slots__ = ("server", "released", "t0", "on_release", "ok", "cls")

    def __init__(self, server: "UploadServer", *, adopted: bool = False,
                 cls: str = "standard"):
        """``adopted``: this slot's capacity was transferred from a
        releasing transfer (queued-request handoff) — _active already
        counts it. The per-CLASS count is maintained here either way:
        class attribution never transfers with the slot."""
        self.server = server
        self.released = False
        self.cls = cls
        server._active_cls[cls] = server._active_cls.get(cls, 0) + 1
        _qos_upload_active.labels(cls).set(server._active_cls[cls])
        self.t0 = time.monotonic()
        # armed just before the response is handed off (serve journal):
        # fires with the measured hold time once the body is fully sent,
        # so serve_ms covers the actual transmit, sendfile included.
        # ``ok`` is set by the response classes only when the transmit
        # COMPLETED — a child that disconnected mid-body must not journal
        # a serve row claiming the full range landed (bytes_served and
        # the seed-uplink bandwidth estimate would inflate under churn)
        self.on_release = None
        self.ok = False
        if not adopted:
            server._active += 1
            _upload_active.set(server._active)

    def release(self) -> None:
        if not self.released:
            self.released = True
            srv = self.server
            srv._active_cls[self.cls] = max(
                0, srv._active_cls.get(self.cls, 0) - 1)
            _qos_upload_active.labels(self.cls).set(
                srv._active_cls[self.cls])
            # feed the busy-hint EWMA with the observed hold time
            held_ms = (time.monotonic() - self.t0) * 1000.0
            srv._transfer_ms = (0.8 * srv._transfer_ms + 0.2 * held_ms
                                if srv._transfer_ms > 0 else held_ms)
            srv._transfer_ms_at = time.monotonic()
            if self.on_release is not None:
                self.on_release(held_ms)
            # hand the slot STRAIGHT to the longest-queued request
            # (ownership transfer, _active unchanged): decrementing first
            # would let a fresh arrival's gate check win the race against
            # the woken waiter's resume — inverted fairness where the
            # longest-waiting request is the one that 503s
            srv._pass_on_slot()


class _SlotFileResponse(web.FileResponse):
    """FileResponse whose slot is held across the sendfile: aiohttp's
    FileResponse transmits the body inside ``prepare()``."""

    def __init__(self, path, slot: _Slot, **kwargs):
        super().__init__(path, **kwargs)
        self._slot = slot

    async def prepare(self, request):
        try:
            result = await super().prepare(request)
            self._slot.ok = True        # sendfile body fully transmitted
            return result
        finally:
            self._slot.release()


class _SlotResponse(web.Response):
    """Buffered response whose slot is held until write_eof (body bytes are
    written by the server after the handler returns). prepare() also
    releases on failure: a client that disconnects before the body is sent
    makes aiohttp raise in prepare() and never call write_eof — without
    this, each such disconnect leaks a slot until the peer 503s forever."""

    def __init__(self, slot: _Slot, **kwargs):
        super().__init__(**kwargs)
        self._slot = slot

    async def prepare(self, request):
        try:
            return await super().prepare(request)
        except BaseException:
            self._slot.release()
            raise

    async def write_eof(self, data: bytes = b""):
        try:
            result = await super().write_eof(data)
            self._slot.ok = True        # buffered body fully transmitted
            return result
        finally:
            self._slot.release()


class UploadServer:
    # Concurrent piece transfers served at once when the daemon config says
    # "auto" (0). Beyond this the server answers 503 and the requesting
    # child reroutes to another holder — per-transfer backpressure is what
    # stops every starved child of a fan-out from pulling each fresh piece
    # straight off the seed (the NIC would be split N ways and the mesh
    # would never carry a byte). A few concurrent transfers keep the NIC
    # full; more only dilute each one.
    DEFAULT_CONCURRENT_LIMIT = 6
    # how long a request may queue for a slot before 503ing (see the gate)
    SLOT_WAIT_S = 0.2

    # max bytes moved per streaming-relay write: bounds the on-loop copy
    # from a live span's buffer and keeps the limiter granular
    RELAY_CHUNK = 1 << 20

    def __init__(self, storage_mgr: StorageManager, *, port: int = 0,
                 rate_limit_bps: int = 0, concurrent_limit: int = 0,
                 bulk_concurrent_limit: int = 0,
                 host: str = "0.0.0.0", debug_endpoints: bool = False,
                 flight_recorder=None, pex=None, relay=None,
                 relay_stall_s: float = 10.0, qos=None, verdicts=None):
        self.storage_mgr = storage_mgr
        self.flight_recorder = flight_recorder
        self.pex = pex
        self.verdicts = verdicts            # VerdictLedger (/debug/verdicts)
        # this daemon's host id, set by the bootstrap: scopes the
        # ``upload.serve`` faultgate key so a chaos run (or a co-resident
        # test pod) can poison exactly ONE daemon's serves
        self.host_id = ""
        self.relay = relay                  # RelayHub (None = store-and-forward)
        self.relay_stall_s = relay_stall_s  # per-wait watermark deadline
        self.qos = qos                      # QosGovernor (GET /debug/qos)
        self.host = host
        self.port = port
        self.tls: tuple[str, str, str] | None = None   # (cert, key, ca)
        self.tls_policy = "force"      # see rpc/mux.py POLICIES
        self.mux = None                # MuxListener when rollout-muxing
        self.limiter = TokenBucket(rate_limit_bps or 0)
        self.concurrent_limit = concurrent_limit or self.DEFAULT_CONCURRENT_LIMIT
        # class-aware admission (QoS): bulk-class GETs may hold at most
        # this many of the slots; the remainder stays reserved for
        # critical/standard children, so a bulk herd saturates its share
        # of the gate without ever starving the foreground of a slot
        self.bulk_limit = bulk_concurrent_limit \
            or max(1, self.concurrent_limit - 2)
        self.debug_endpoints = debug_endpoints
        self._active = 0
        self._active_cls: dict[str, int] = {}
        self._transfer_ms = 0.0     # EWMA slot-hold time -> 503 retry hint
        self._transfer_ms_at = 0.0  # when the EWMA last saw a real transfer
        self._slot_waiters: deque = deque()
        self._bulk_waiters: deque = deque()   # bulk queues behind ALL others
        self._runner: web.AppRunner | None = None

    def _pass_on_slot(self) -> None:
        """Give a freed (or orphaned) slot to the next LIVE waiter, else
        return it to capacity. Cancelled futures (timed-out or disconnected
        waiters) are skipped — setting a result on one would strand the
        slot forever (the r04 leak: seed gate stuck at 5/6 after one
        client disconnected while queued). Non-bulk waiters always wake
        first; a bulk waiter only when the bulk cap has headroom — the
        class-aware half of the gate."""
        while self._slot_waiters:
            fut = self._slot_waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return
        if self._active_cls.get("bulk", 0) < self.bulk_limit:
            while self._bulk_waiters:
                fut = self._bulk_waiters.popleft()
                if not fut.done():
                    fut.set_result(None)
                    return
        self._active -= 1
        _upload_active.set(self._active)

    async def start(self) -> None:
        async def healthy(_r: web.Request) -> web.Response:
            return web.Response(text="ok")

        async def metrics(_r: web.Request) -> web.Response:
            return web.Response(text=REGISTRY.expose())

        app = web.Application()
        app.router.add_get("/download/{prefix}/{task_id}", self._traced)
        app.router.add_get("/healthy", healthy)
        app.router.add_get("/metrics", metrics)
        if self.flight_recorder is not None:
            # read-only + ring-bounded, so served like /metrics rather
            # than behind the profiling flag
            from .flight_recorder import add_flight_routes
            add_flight_routes(app.router, self.flight_recorder)
        # runtime health snapshot (loop lag, watchdog, SLO breaches) —
        # read-only like /debug/flight, so always on: a wedged daemon's
        # health surface existing only behind a flag defeats its purpose
        from ..common.health import add_health_routes
        add_health_routes(app.router)
        if self.qos is not None:
            # QoS plane readout (degradation state, per-class admission /
            # shed counters, per-tenant attribution) — read-only, always
            # on for the same reason as /debug/health: a browned-out
            # daemon must be diagnosable (dfdiag --qos)
            from .qos import add_qos_routes
            add_qos_routes(app.router, self.qos)
        if self.pex is not None:
            # PEX gossip exchange + swarm debug view (GET/POST /pex/digest,
            # GET /debug/pex): mesh-internal like the piece routes, so it
            # rides the same port and TLS posture
            from .pex import add_pex_routes
            add_pex_routes(app.router, self.pex)
        if self.verdicts is not None:
            # per-parent verdict ledger readout (GET /debug/verdicts):
            # read-only + bounded like /debug/flight, always on — dfdiag
            # --pod sweeps it to name shunned/self-quarantined hosts
            from .verdicts import add_verdict_routes
            add_verdict_routes(app.router, self.verdicts)
        if self.debug_endpoints:
            # pprof-equivalent debug surface (reference cmd/dependency
            # InitMonitor --pprof-port) — OFF by default: profiling slows
            # every Python call on the loop thread, and this port is
            # reachable by any mesh peer
            from ..common.debug_http import add_debug_routes
            add_debug_routes(app.router)
            # fault-injection control plane (tools/stress.py --chaos):
            # gated with the debug surface because arming scripts mutates
            # live behaviour
            from ..common.faultgate import add_fault_routes
            add_fault_routes(app.router)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        ssl_ctx = None
        if self.tls is not None:
            # the DATA plane carries the actual piece bytes: under fleet
            # mTLS it serves the issued leaf and REQUIRES a fleet client
            # cert, or "mTLS" would protect metadata while every artifact
            # crosses the wire in clear
            import ssl as _ssl
            cert, key, ca = self.tls
            ssl_ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(cert, key)
            ssl_ctx.load_verify_locations(cafile=ca)
            ssl_ctx.verify_mode = _ssl.CERT_REQUIRED
        if ssl_ctx is not None and self.tls_policy != "force":
            # TLS rollout on the DATA plane too (same contract as the rpc
            # mux, rpc/mux.py): one public port serves plaintext AND mTLS
            # via a peeking front over unix-socket backends, so the piece
            # plane upgrades without a fleet flag day. Flip .mux.policy to
            # "force" at runtime to retire plaintext for new connections.
            from ..rpc.mux import MuxListener
            plain_sock, tls_sock = MuxListener.backend_sockets()
            await web.UnixSite(self._runner, plain_sock).start()
            await web.UnixSite(self._runner, tls_sock,
                               ssl_context=ssl_ctx).start()
            self.mux = MuxListener(self.host, self.port,
                                   plain_sock=plain_sock, tls_sock=tls_sock,
                                   policy=self.tls_policy)
            await self.mux.start()
            self.port = self.mux.port
        else:
            site = web.TCPSite(self._runner, self.host, self.port,
                               ssl_context=ssl_ctx)
            await site.start()
            self.port = resolve_port(self._runner)
        log.info("upload server on %s:%d (tls=%s, policy=%s)", self.host,
                 self.port, self.tls is not None,
                 self.tls_policy if self.tls is not None else "-")

    async def stop(self) -> None:
        if self.mux is not None:
            await self.mux.stop()
        if self._runner:
            await self._runner.cleanup()
        if self.mux is not None:
            self.mux.cleanup_backend_files()

    @staticmethod
    def _progress_headers(ts) -> dict:
        """The advertised landing watermark (``X-DF-Piece-Progress``):
        pieces landed / total, on every piece response — the wire half of
        the piece-progress signal (a child sees how complete the holder
        it is pulling from is)."""
        md = getattr(ts, "md", None)
        pieces = getattr(md, "pieces", None)
        if pieces is None:
            return {}
        total = getattr(md, "total_piece_count", -1)
        return {"X-DF-Piece-Progress": f"{len(pieces)}/{total}"}

    def _arm_serve_journal(self, slot: _Slot, request: web.Request, ts,
                           rng, *, wait_ms: float,
                           relayed: bool = False) -> None:
        """Arm the slot to journal this serve once the body is fully sent:
        one UPLOAD edge row (requesting peer, piece idx, bytes, slot-hold
        serve ms, limiter-wait ms) on the task's flight — the parent half
        of the transfer edge podscope stitches pod-wide, observable even
        on the scheduler-less pex rung where no control plane saw it."""
        _upload_wait_secs.observe(wait_ms / 1000.0)
        # the id the child addressed us by (same as storage's), present
        # for every piece route — storage test fakes may carry no md id
        task_id = request.match_info["task_id"]
        piece_size = getattr(ts.md, "piece_size", 0)
        # a grouped span GET is one row spanning several pieces: journal
        # the first index + the span count so the parent-side piece
        # tally agrees with the child's per-piece rows
        piece = rng.start // piece_size if piece_size > 0 else -1
        span = (-(-rng.length // piece_size) if piece_size > 0 else 1)
        peer_id = request.query.get("peerId", "")
        addr = request.remote or ""
        nbytes = rng.length

        def journal(held_ms: float) -> None:
            if not slot.ok:
                return     # transmit aborted: the child never got the range
            _upload_serve_secs.observe(held_ms / 1000.0)
            # popularity feed for the storage GC (castore.py): what this
            # daemon actually serves is what eviction should keep. Ranged
            # sub-task views credit their PARENT — eviction is decided by
            # parent task id, and crediting the subtask id would leave the
            # hottest ranged content scoring 0.0 at the GC
            castore = getattr(self.storage_mgr, "castore", None)
            if castore is not None:
                parent = getattr(ts, "parent", None)
                md = getattr(parent, "md", None) or getattr(ts, "md", None)
                castore.record_serve(getattr(md, "task_id", task_id),
                                     nbytes)
            # flight resolved only NOW, once the transmit is known good:
            # serving() may have to evict another serve-only flight to
            # admit this task, and an aborted transfer must not pay that
            # price for a row it will never write
            if self.flight_recorder is not None:
                flight = self.flight_recorder.serving(task_id)
                if flight is not None:
                    flight.serve(peer=peer_id, addr=addr, piece=piece,
                                 nbytes=nbytes, serve_ms=held_ms,
                                 wait_ms=wait_ms, pieces=span,
                                 relayed=relayed)

        slot.on_release = journal

    async def _traced(self, request: web.Request) -> web.StreamResponse:
        """Server half of the piece-request trace: the child's traceparent
        rides the GET (piece_downloader) and this span joins its trace, so
        one trace id follows a slow transfer across both daemons."""
        parent = tracing.from_traceparent(
            request.headers.get("traceparent", ""))
        if parent is None and not tracing.TRACER.enabled:
            return await self._handle(request)
        with tracing.span("upload.serve", parent=parent,
                          peer=request.query.get("peerId", "")[-16:],
                          range=request.headers.get("Range", "")) as sp:
            resp = await self._handle(request)
            sp.set(status=resp.status)
            return resp

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        task_id = request.match_info["task_id"]
        ts = self.storage_mgr.get(task_id)
        if ts is None:
            _upload_reqs.labels("404").inc()
            raise web.HTTPNotFound(text=f"task {task_id[:12]} not found")
        total = ts.md.content_length
        rng_header = request.headers.get("Range", "")
        if not rng_header:
            _upload_reqs.labels("400").inc()
            raise web.HTTPBadRequest(text="Range header required for piece reads")
        try:
            limit = total if total >= 0 else (1 << 62)
            rng = parse_http_range(rng_header, limit)
        except ValueError as exc:
            _upload_reqs.labels("416").inc()
            raise web.HTTPRequestRangeNotSatisfiable(text=str(exc))
        has = getattr(ts, "has_range", None)
        streaming = False
        if has is not None and not has(rng.start, rng.length):
            if self.relay is not None and self.relay.active(task_id):
                # cut-through relay: the task is mid-landing on this
                # daemon — stream the range against the landing watermark
                # (serve what has arrived, await the rest with a bounded
                # deadline) instead of 416ing on an incomplete piece
                streaming = True
            else:
                _upload_reqs.labels("416").inc()
                raise web.HTTPRequestRangeNotSatisfiable(
                    text=f"bytes {rng.start}+{rng.length} not stored yet")
        # the requesting child's QoS class rides the GET (?cls=, from
        # piece_downloader): bulk is additionally capped at bulk_limit
        # slots and queues behind every non-bulk waiter
        cls = request.query.get("cls", "")
        if cls not in ("critical", "standard", "bulk"):
            cls = "standard"
        is_bulk = cls == "bulk"
        waiters = self._bulk_waiters if is_bulk else self._slot_waiters
        gate_closed = (self._active >= self.concurrent_limit
                       or self._slot_waiters
                       or (is_bulk
                           and (self._bulk_waiters
                                or self._active_cls.get("bulk", 0)
                                >= self.bulk_limit)))
        slot = None
        if gate_closed:
            # bounded slot wait BEFORE 503ing: when the gate is full but
            # moving, queueing ~one transfer-time is far cheaper than the
            # client's error round-trip + re-dispatch. Only a gate that
            # stays saturated past the wait answers 503 — with a measured
            # retry hint, so clients back off for one observed transfer
            # time instead of hammering (the r04 storm: 40 ms blind retries
            # against a seed mid-transfer outnumbered real downloads).
            # Fresh arrivals queue behind existing waiters (FIFO); a
            # releasing transfer hands its slot to the queue head.
            deadline = time.monotonic() + self.SLOT_WAIT_S
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _upload_reqs.labels("503").inc()
                    _qos_upload_shed.labels(cls).inc()
                    # a congested-era EWMA must not dictate backoffs after
                    # the burst has passed (one bad wave would slow every
                    # later one): hints older than ~10 transfer-times decay
                    # to the floor
                    ewma = self._transfer_ms
                    age_ms = (time.monotonic() - self._transfer_ms_at) * 1e3
                    if ewma > 0 and age_ms > 10 * max(ewma, 100.0):
                        ewma = 0.0
                    hint_ms = int(min(max(ewma, 50.0), 2000.0))
                    raise web.HTTPServiceUnavailable(
                        text="upload concurrency limit",
                        headers={"Retry-After": str(-(-hint_ms // 1000)),
                                 "X-Retry-After-Ms": str(hint_ms)})
                fut = asyncio.get_running_loop().create_future()
                waiters.append(fut)
                try:
                    await asyncio.wait_for(fut, remaining)
                except asyncio.TimeoutError:
                    if fut.done() and not fut.cancelled():
                        # transfer landed exactly at the deadline: take it
                        slot = _Slot(self, adopted=True, cls=cls)
                        break
                    continue   # loop re-checks the deadline and 503s
                except BaseException:
                    # request died while queued (client disconnect -> task
                    # cancel). A transfer may have landed on our future in
                    # the same tick: re-home it, never strand it.
                    if fut.done() and not fut.cancelled():
                        self._pass_on_slot()
                    else:
                        fut.cancel()
                    raise
                # a releasing transfer handed us its slot (ownership
                # transfer — _active already counts it)
                slot = _Slot(self, adopted=True, cls=cls)
                break
        if slot is None:
            # held until the BODY is sent (slot classes)
            slot = _Slot(self, cls=cls)
        try:
            if streaming:
                return await self._serve_relay(request, ts, rng, slot,
                                               task_id)
            # byzantine chaos (site ``upload.serve``, keyed
            # "<host_id>|<task_id>"): while a corrupt script is armed for
            # this daemon, serves route through the buffered path (a
            # sendfile body never enters Python, so it cannot be flipped)
            # and the read bytes get the scripted corruption — the swarm
            # immune system's proving lever (stress --byzantine)
            fkey = f"{self.host_id}|{task_id}"
            poisoned = faultgate.ARMED and faultgate.peek(
                "upload.serve", fkey, kinds=frozenset({"corrupt"}))
            # whole-file tasks: serve via sendfile (FileResponse honors
            # Range) so piece bytes never enter Python — the upload path is
            # the hottest loop on a seed peer.
            data_path = getattr(ts, "data_path", None)
            if data_path is not None and total >= 0 and not poisoned:
                wait_t0 = time.monotonic()
                # dflint: disable=DF008 — sendfile serve: after return the bytes move in-kernel with no failure callback; a dropped send is accounted as moved by design (the disk-read branch below is the refundable one)
                await self.limiter.acquire(rng.length)
                _upload_bytes.inc(rng.length)
                _upload_piece_bytes.observe(rng.length)
                _upload_reqs.labels("206").inc()
                self._arm_serve_journal(
                    slot, request, ts, rng,
                    wait_ms=(time.monotonic() - wait_t0) * 1000.0)
                return _SlotFileResponse(data_path(), slot,
                                         headers=self._progress_headers(ts))
            # acquire BEFORE the read, matching the sendfile branch: a
            # rate-limited seed must not buffer a multi-MiB range it then
            # sits on for the whole token wait (the bytes pin memory and
            # go cold while the limiter holds them back)
            wait_t0 = time.monotonic()
            await self.limiter.acquire(rng.length)
            # wait_ms measured HERE, not at arm time: the storage read
            # below must not masquerade as limiter wait in the serve
            # journal (dfdiag would blame rate limiting for a slow disk)
            wait_ms = (time.monotonic() - wait_t0) * 1000.0
            try:
                # dedicated storage executor: piece serves never queue
                # behind the default pool's TLS handshakes (or vice versa)
                data = await run_io(ts.read_range, rng.start, rng.length)
            except (DFError, OSError) as exc:
                # read_range wraps IO failure in DFError (evicted task ->
                # missing data file); OSError belt-and-braces for storage
                # impls that don't. The bytes were never moved: hand the
                # tokens back (same contract as acquire's cancel path), or
                # leechers retrying a just-GC'd hot task would drain the
                # rate budget with 404s and throttle real serves
                self.limiter.refund(rng.length)
                _upload_reqs.labels("404").inc()
                msg = exc.message if isinstance(exc, DFError) else str(exc)
                raise web.HTTPNotFound(text=msg)
            except BaseException:
                # cancelled mid-read (client disconnect, peer's per-piece
                # deadline): zero bytes served, so the tokens go back —
                # otherwise deadline churn drains a rate-limited seed's
                # budget with aborted requests
                self.limiter.refund(rng.length)
                raise
            if poisoned:
                # scripted byte-flip on this served range: the child's
                # landing verification catches it, reports a ``corrupt``
                # verdict, and the quarantine plane takes it from there
                data = faultgate.corrupt("upload.serve", data, key=fkey)
            _upload_bytes.inc(len(data))
            _upload_piece_bytes.observe(len(data))
            _upload_reqs.labels("206").inc()
            self._arm_serve_journal(slot, request, ts, rng,
                                    wait_ms=wait_ms)
            return _SlotResponse(
                slot, status=206, body=data,
                headers={"Content-Range":
                         f"bytes {rng.start}-{rng.end - 1}/{total}",
                         "Content-Type": "application/octet-stream",
                         **self._progress_headers(ts)})
        except BaseException:
            # never reached the transfer: give the slot back here (the
            # response's own release only runs once it is being sent)
            slot.release()
            raise

    async def _serve_relay(self, request: web.Request, ts, rng,
                           slot: _Slot, task_id: str) -> web.StreamResponse:
        """Cut-through range serve: stream bytes up to the landing
        frontier (verified pieces on disk + the live span's watermark),
        awaiting further progress with a bounded per-wait deadline.

        Outcomes: complete (the whole range streamed — possibly before
        this daemon itself finished the piece, which IS the point);
        stalled-before-first-byte (503 with a retry hint — the child
        requeues without a strike, like any busy parent); stalled or
        evicted mid-stream (connection aborted — the child's short read
        requeues the piece against another holder). Limiter tokens are
        acquired per chunk just before the write and refunded when that
        chunk's bytes never moved (eviction/cancel), the same contract as
        the 404 path."""
        relay = self.relay
        total = ts.md.content_length
        landed, total_pieces = relay.progress(task_id, ts)
        resp = web.StreamResponse(
            status=206,
            headers={"Content-Range":
                     f"bytes {rng.start}-{rng.end - 1}/"
                     f"{total if total >= 0 else '*'}",
                     "Content-Type": "application/octet-stream",
                     "X-DF-Piece-Progress": f"{landed}/{total_pieces}",
                     "X-DF-Relay": "1"})
        resp.content_length = rng.length
        pos = rng.start
        wait_s = 0.0
        limiter_ms = 0.0
        # "aborted" covers exits that never set a verdict (client
        # disconnect/cancel mid-stream) — they must not count as "ok"
        result = "aborted"
        # the stall deadline re-arms ONLY when THIS reader's frontier
        # moves: task-wide progress pulses wake the wait, but a serve
        # parked at an offset that never advances must still expire in
        # relay_stall_s even while other pieces keep landing — otherwise
        # a dead announce-ahead piece holds an upload slot for the rest
        # of the task's lifetime
        stall_at = time.monotonic() + self.relay_stall_s
        last_avail = pos
        # byzantine chaos on the cut-through path: ONE corrupt attempt
        # per SERVE (consumed on the first chunk — one flipped byte
        # already fails the containing piece), so the pct stride keeps
        # its per-serve semantics instead of advancing per chunk
        poison_pending = faultgate.ARMED and faultgate.peek(
            "upload.serve", f"{self.host_id}|{task_id}",
            kinds=frozenset({"corrupt"}))
        try:
            while pos < rng.end:
                if faultgate.ARMED:
                    # 'hang' models an upstream whose watermark stopped
                    # advancing — bounded by the SAME stall deadline a
                    # real dead watermark gets, so the serve degrades
                    # (503/abort, slot released) instead of wedging; the
                    # child's per-piece deadline usually fires first
                    try:
                        await asyncio.wait_for(
                            faultgate.fire("relay.stall", key=task_id),
                            self.relay_stall_s)
                    except asyncio.TimeoutError:
                        result = "stall"
                        _relay_stalls.inc()
                        break
                avail = relay.available_end(task_id, ts, pos, rng.end)
                if avail > last_avail:
                    last_avail = avail
                    stall_at = time.monotonic() + self.relay_stall_s
                if avail <= pos:
                    if not relay.active(task_id):
                        # task finished under us without covering the
                        # rest (failed / piece rejected at landing)
                        result = "abandoned"
                        break
                    remaining = stall_at - time.monotonic()
                    if remaining <= 0:
                        result = "stall"
                        _relay_stalls.inc()
                        break
                    w0 = time.monotonic()
                    await relay.wait_progress(task_id, remaining)
                    wait_s += time.monotonic() - w0
                    continue
                n = min(self.RELAY_CHUNK, avail - pos)
                try:
                    chunk = relay.read_span(task_id, pos, n)
                    src = "span"
                    if chunk is None:
                        # landed region: read the verified bytes from
                        # disk — clamped to what the piece table says is
                        # ACTUALLY on disk at ``pos`` (the frontier may
                        # extend into a live span whose base is past
                        # pos; pread there would return unwritten file
                        # space and serve it as content)
                        covered = getattr(ts, "covered_prefix", None)
                        hi = (covered(pos, pos + n) if covered is not None
                              else pos + n)
                        if hi <= pos:
                            # raced: the span retired/landed between the
                            # avail check and the read — re-check
                            await relay.wait_progress(task_id, 0.05)
                            continue
                        chunk = await run_io(ts.read_range, pos, hi - pos)
                        src = "storage"
                except (DFError, OSError):
                    # task evicted mid-stream: abort (no tokens held —
                    # they are acquired below, for bytes that move)
                    result = "evicted"
                    break
                if not chunk:
                    # short disk read (frontier raced): re-check, no spin
                    await relay.wait_progress(task_id, 0.05)
                    continue
                if poison_pending:
                    poison_pending = False
                    chunk = faultgate.corrupt(
                        "upload.serve", chunk,
                        key=f"{self.host_id}|{task_id}")
                # tokens for EXACTLY the bytes about to move (a span read
                # clamps at its watermark, a disk read at the covered
                # frontier — charging the pre-clamp size would leak
                # reserved bandwidth on every boundary chunk)
                l0 = time.monotonic()
                await self.limiter.acquire(len(chunk))
                limiter_ms += (time.monotonic() - l0) * 1000.0
                try:
                    if resp.prepared is False:
                        await resp.prepare(request)
                    await resp.write(chunk)
                except BaseException:
                    # the write never completed: refund (PR 5 contract)
                    self.limiter.refund(len(chunk))
                    raise
                _relay_bytes.labels(src).inc(len(chunk))
                _upload_bytes.inc(len(chunk))
                pos += len(chunk)
            if pos >= rng.end:
                # eof INSIDE the try, BEFORE the journal fires: a child
                # that disconnected on the last chunk makes write_eof
                # raise, and the serve must then journal as aborted —
                # not as a completed transfer (the _Slot.ok contract)
                await resp.write_eof()
                result = "ok"
        finally:
            _relay_wait_secs.observe(wait_s)
            _relay_serves.labels(result).inc()
            if result == "ok":
                _upload_reqs.labels("206").inc()
                _upload_piece_bytes.observe(rng.length)
                self._arm_serve_journal(slot, request, ts, rng,
                                        wait_ms=limiter_ms, relayed=True)
                slot.ok = True
            slot.release()
        if result == "ok":
            return resp
        if not resp.prepared:
            # nothing sent yet: a clean 503 with the stall as the hint —
            # the child backs off and requeues without a failure strike
            _upload_reqs.labels("503").inc()
            raise web.HTTPServiceUnavailable(
                text=f"relay {result}: watermark not advancing",
                headers={"Retry-After": "1",
                         "X-Retry-After-Ms": "500"})
        # mid-stream stall/eviction: abort the connection so the child
        # sees a short read (CLIENT_PIECE_DOWNLOAD_FAIL -> requeue against
        # another holder) instead of a clean-looking EOF
        transport = request.transport
        if transport is not None:
            transport.close()
        raise ConnectionResetError(f"relay serve aborted: {result}")


