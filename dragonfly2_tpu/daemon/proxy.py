"""HTTP(S) proxy + registry mirror: transparent P2P for HTTP(S) fetches.

Role parity: reference ``client/daemon/proxy/`` — a forward proxy whose
regex rules decide P2P vs direct (``transport.go:223 NeedUseDragonfly``),
a registry-mirror mode rewriting relative paths onto the upstream registry
(how containerd pulls layers through the mesh), CONNECT handling with
HTTPS interception (``proxy.go:268`` + per-host leaf certs,
``cert.go:37``), and an SNI listener (``proxy_sni.go:32``) for clients
that resolve the registry's name straight to the daemon.

With ``hijack`` on, a CONNECT to a matching host is answered 200 and the
client socket is upgraded to TLS using a CA-signed leaf for that host
(certs.py); the decrypted requests then flow through the same P2P/direct
routing as plain HTTP — TLS registries stop bypassing the mesh. Without it
CONNECT stays a blind byte tunnel.

Implemented as a raw asyncio server: aiohttp's server can't speak CONNECT.
"""

from __future__ import annotations

import asyncio
import logging
import re
import ssl
from urllib.parse import urlsplit

import aiohttp

from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import UrlMeta
from .config import ProxyConfig

log = logging.getLogger("df.http.proxy")


async def _writer_start_tls(writer: asyncio.StreamWriter,
                            ctx: ssl.SSLContext) -> None:
    """``StreamWriter.start_tls`` exists only on Python >= 3.11; on 3.10
    drive ``loop.start_tls`` directly (the same thing 3.11's method does)
    and swap the writer's transport for the TLS one. The reader needs no
    rewiring: the SSL protocol delivers decrypted bytes to the same
    StreamReaderProtocol underneath."""
    if hasattr(writer, "start_tls"):
        await writer.start_tls(ctx)
        return
    await writer.drain()
    loop = asyncio.get_running_loop()
    transport = writer.transport
    new_transport = await loop.start_tls(
        transport, transport.get_protocol(), ctx, server_side=True)
    writer._transport = new_transport  # noqa: SLF001 - no public hook on 3.10

_proxy_reqs = REGISTRY.counter("df_proxy_requests_total",
                               "proxy requests", ("route",))
_proxy_bytes = REGISTRY.counter("df_proxy_bytes_total",
                                "bytes returned to proxy clients", ("route",))

# registry blob digests are content-addressed: the P2P sweet spot
BLOB_RE = re.compile(r"/blobs/sha256:[0-9a-f]{64}$")


class ProxyServer:
    def __init__(self, daemon, cfg: ProxyConfig):
        self.daemon = daemon
        self.cfg = cfg
        self.rules = [re.compile(r) for r in cfg.rules]
        self.direct_rules = [re.compile(r) for r in cfg.direct_rules]
        self.hijack_rules = [re.compile(r) for r in cfg.hijack_hosts]
        self.port = cfg.port
        self.sni_port = cfg.sni_port
        self._server: asyncio.Server | None = None
        self._sni_server: asyncio.Server | None = None
        self._client: aiohttp.ClientSession | None = None
        self._issuer = None
        if cfg.hijack or cfg.sni_port:
            from ..common.certs import CertIssuer
            self._issuer = CertIssuer(
                daemon.cfg.workdir, ca_cert_path=cfg.ca_cert,
                ca_key_path=cfg.ca_key)

    @property
    def ca_cert_path(self) -> str:
        """The CA file clients/containerd must trust when hijack is on."""
        return self._issuer.ca_cert_path if self._issuer else ""

    # Listen backlog: asyncio's default is 100, and a container-runtime
    # startup burst (hundreds of layer pulls dialing the proxy in one
    # tick) overflows it — the kernel then RSTs queued connections and
    # clients see "server disconnected" with zero server-side log
    # (tests/test_concurrency.py::TestProxyConcurrency at 200+).
    BACKLOG = 1024

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.daemon.cfg.listen_ip, self.port,
            backlog=self.BACKLOG)
        self.port = self._server.sockets[0].getsockname()[1]
        # upstream trust for relayed (non-P2P) fetches mirrors the source
        # client's: a private-CA registry must work for manifests/auth too,
        # not just the blob path (which goes through HTTPSourceClient)
        upstream_ssl = None
        if not self.cfg.verify_upstream:
            upstream_ssl = False
        elif self.daemon.cfg.download.source_ca:
            # private CA ADDED to system trust, not replacing it
            upstream_ssl = ssl.create_default_context()
            upstream_ssl.load_verify_locations(
                cafile=self.daemon.cfg.download.source_ca)
        self._client = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=300.0),
            auto_decompress=False,
            connector=aiohttp.TCPConnector(ssl=upstream_ssl))
        if self.sni_port:
            self._sni_server = await asyncio.start_server(
                self._handle_sni_conn, self.daemon.cfg.listen_ip,
                max(self.sni_port, 0), ssl=self._sni_ssl_context(),
                backlog=self.BACKLOG)
            self.sni_port = self._sni_server.sockets[0].getsockname()[1]
            log.info("SNI proxy on :%d", self.sni_port)
        log.info("proxy on :%d (mirror=%s, %d p2p rules, hijack=%s)",
                 self.port, self.cfg.registry_mirror or "-", len(self.rules),
                 self.cfg.hijack)

    async def stop(self) -> None:
        for srv in (self._server, self._sni_server):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        if self._client is not None:
            await self._client.close()

    # ------------------------------------------------------------------

    def use_p2p(self, url: str) -> bool:
        for rule in self.direct_rules:
            if rule.search(url):
                return False
        for rule in self.rules:
            if rule.search(url):
                return True
        # default: registry blobs ride the mesh, everything else is direct
        return bool(BLOB_RE.search(urlsplit(url).path))

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            await self._serve_http_loop(reader, writer, scheme="http")
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, ssl.SSLError):
            pass
        except Exception:  # noqa: BLE001 - connection boundary
            log.exception("proxy connection failed")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except OSError:
                pass

    async def _handle_sni_conn(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter) -> None:
        """TLS connections from clients that resolved the registry's name to
        this daemon (reference ``proxy_sni.go``): asyncio completed the
        handshake with an SNI-minted leaf; inner requests are origin-form
        with a Host header and route exactly like hijacked CONNECTs."""
        try:
            sslobj = writer.get_extra_info("ssl_object")
            sni = getattr(sslobj, "_df_sni", "") if sslobj else ""
            await self._serve_http_loop(reader, writer, scheme="https",
                                        authority=sni)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, ssl.SSLError):
            pass
        except Exception:  # noqa: BLE001 - connection boundary
            log.exception("sni proxy connection failed")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except OSError:
                pass

    def _sni_ssl_context(self) -> ssl.SSLContext:
        """Base server context whose SNI callback swaps in a leaf minted for
        whatever name the client asked for (reference ``proxy_sni.go``'s
        GetCertificate)."""
        assert self._issuer is not None
        issuer = self._issuer
        base = issuer.server_context(self.daemon.cfg.host_ip or "localhost")

        def on_sni(sslobj, servername, _ctx):
            # sync by protocol contract (ssl module callback); leaf minting
            # is ~1ms EC keygen and one-time per host (cached 24h)
            if servername:
                sslobj.context = issuer.server_context(servername)
                sslobj._df_sni = servername   # routing fallback (no Host)

        base.sni_callback = on_sni
        return base

    def _hijack_match(self, host: str) -> bool:
        if not self.hijack_rules:
            return True                  # hijack on = intercept everything
        return any(r.search(host) for r in self.hijack_rules)

    async def _serve_http_loop(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter, *,
                               scheme: str,
                               authority: str = "") -> None:
        while True:
            request_line = await reader.readline()
            if not request_line:
                return
            try:
                method, target, version = \
                    request_line.decode("latin1").split(" ", 2)
            except ValueError:
                writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
                return
            headers = await self._read_headers(reader)
            if method.upper() == "CONNECT":
                host = target.partition(":")[0]
                if (self._issuer is not None and self.cfg.hijack
                        and self._hijack_match(host)):
                    # pause the transport BEFORE the 200: the client fires
                    # its ClientHello the instant it sees the reply, and any
                    # bytes the plaintext reader consumes before start_tls
                    # swaps protocols are lost to the handshake (flaky
                    # deadlock, window widened by the off-loop cert mint)
                    writer.transport.pause_reading()
                    writer.write(b"HTTP/1.1 200 Connection Established\r\n\r\n")
                    await writer.drain()
                    # keygen + signing + file IO off-loop (first hit per host)
                    ctx = await asyncio.to_thread(
                        self._issuer.server_context, host)
                    # asyncio infers server_side=True for start_server
                    # streams; the TLS transport resumes reading itself
                    await _writer_start_tls(writer, ctx)
                    _proxy_reqs.labels("hijack").inc()
                    scheme, authority = "https", target
                    continue        # decrypted requests re-enter this loop
                await self._tunnel(target, reader, writer)
                return
            keep_alive = await self._handle_request(
                method.upper(), target, headers, reader, writer,
                scheme=scheme, authority=authority)
            if not keep_alive:
                return

    @staticmethod
    async def _read_headers(reader: asyncio.StreamReader) -> dict[str, str]:
        headers: dict[str, str] = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                return headers
            key, _, value = line.decode("latin1").partition(":")
            headers[key.strip().lower()] = value.strip()

    # ------------------------------------------------------------------

    async def _tunnel(self, target: str, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        """CONNECT: blind byte tunnel (TLS passes through unmodified)."""
        host, _, port_s = target.partition(":")
        try:
            up_r, up_w = await asyncio.open_connection(host,
                                                       int(port_s or 443))
        except OSError as exc:
            writer.write(b"HTTP/1.1 502 Bad Gateway\r\n\r\n")
            await writer.drain()
            log.debug("CONNECT %s failed: %s", target, exc)
            return
        _proxy_reqs.labels("tunnel").inc()
        writer.write(b"HTTP/1.1 200 Connection Established\r\n\r\n")
        await writer.drain()

        async def pump(src: asyncio.StreamReader,
                       dst: asyncio.StreamWriter) -> None:
            try:
                while True:
                    data = await src.read(64 * 1024)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass
            finally:
                try:
                    dst.close()
                except OSError:
                    pass

        await asyncio.gather(pump(reader, up_w), pump(up_r, writer))

    # ------------------------------------------------------------------

    def _resolve_url(self, target: str, headers: dict[str, str], *,
                     scheme: str = "http", authority: str = "") -> str:
        if target.startswith("http://") or target.startswith("https://"):
            return target                       # forward-proxy form
        # hijacked/SNI TLS: origin-form against the intercepted authority
        if scheme == "https":
            host = headers.get("host", "") or authority
            host = host.removesuffix(":443")
            return f"https://{host}{target}"
        # registry-mirror form: relative path against the upstream registry
        if self.cfg.registry_mirror:
            return self.cfg.registry_mirror.rstrip("/") + target
        host = headers.get("host", "")
        return f"http://{host}{target}"

    async def _handle_request(self, method: str, target: str,
                              headers: dict[str, str],
                              reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter, *,
                              scheme: str = "http",
                              authority: str = "") -> bool:
        url = self._resolve_url(target, headers, scheme=scheme,
                                authority=authority)
        if method == "GET" and self.use_p2p(url):
            return await self._serve_p2p(url, headers, writer)
        return await self._serve_direct(method, url, headers, reader, writer)

    async def _serve_p2p(self, url: str, headers: dict[str, str],
                         writer: asyncio.StreamWriter) -> bool:
        _proxy_reqs.labels("p2p").inc()
        fwd = {k: v for k, v in headers.items()
               if k in ("authorization", "accept", "user-agent")}
        # multi-tenant QoS: the tenant and service class ride standard
        # request headers so any HTTP client (containerd, curl) can tag
        # its traffic without a dragonfly-aware SDK
        meta = UrlMeta(header=fwd or None, tag="proxy",
                       tenant=headers.get("x-dragonfly-tenant", ""),
                       qos_class=headers.get("x-dragonfly-class", ""))
        try:
            task_id, chunks = await self.daemon.ptm.stream_task(url, meta)
        except DFError as exc:
            if exc.code == Code.RESOURCE_EXHAUSTED:
                # QoS shed (brownout) or tenant quota: the 429 contract —
                # Retry-After carries the governor's hint, and the
                # common/retry.py ladder in dragonfly-aware clients (plus
                # any well-behaved HTTP client) backs off instead of
                # hammering the browned-out daemon
                _proxy_reqs.labels("shed").inc()
                retry_ms = getattr(exc, "retry_after_ms", 0) or 1000
                writer.write(
                    b"HTTP/1.1 429 Too Many Requests\r\n"
                    b"Retry-After: " + str(-(-retry_ms // 1000)).encode()
                    + b"\r\nX-Retry-After-Ms: " + str(retry_ms).encode()
                    + b"\r\nConnection: close\r\n\r\n")
                await writer.drain()
                return False
            log.warning("p2p stream for %s failed: %s", url, exc.message)
            writer.write(b"HTTP/1.1 502 Bad Gateway\r\n\r\n")
            await writer.drain()
            return False
        except Exception as exc:  # noqa: BLE001 - task setup failed
            log.warning("p2p stream for %s failed: %s", url, exc)
            writer.write(b"HTTP/1.1 502 Bad Gateway\r\n\r\n")
            await writer.drain()
            return False
        conductor = self.daemon.ptm.conductor(task_id)
        length = conductor.content_length if conductor is not None else -1
        head = "HTTP/1.1 200 OK\r\nContent-Type: application/octet-stream\r\n"
        sent_chunked = length < 0
        if sent_chunked:
            # Connection: close on THIS branch too: the handler closes the
            # socket after one response either way, and a chunked reply
            # without the header let keep-alive clients pool the dead
            # connection — the next request on it saw "server
            # disconnected" with nothing in the proxy log (the early-joiner
            # window before back-source returns content-length, caught by
            # TestProxyConcurrency at 200+ clients)
            head += "Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        else:
            head += f"Content-Length: {length}\r\nConnection: close\r\n\r\n"
        writer.write(head.encode("latin1"))
        try:
            async for chunk in chunks:
                if sent_chunked:
                    writer.write(f"{len(chunk):x}\r\n".encode())
                    writer.write(chunk)
                    writer.write(b"\r\n")
                else:
                    writer.write(chunk)
                _proxy_bytes.labels("p2p").inc(len(chunk))
                await writer.drain()
            if sent_chunked:
                writer.write(b"0\r\n\r\n")
                await writer.drain()
        except Exception as exc:  # noqa: BLE001 - client or mesh went away
            log.debug("p2p stream aborted for %s: %s", url, exc)
            return False
        return False   # Connection: close keeps framing simple

    async def _serve_direct(self, method: str, url: str,
                            headers: dict[str, str],
                            reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> bool:
        _proxy_reqs.labels("direct").inc()
        body = b""
        length = int(headers.get("content-length", "0") or 0)
        if length:
            body = await reader.readexactly(length)
        fwd = {k: v for k, v in headers.items()
               if k not in ("proxy-connection", "connection", "host",
                            "content-length")}
        assert self._client is not None
        try:
            async with self._client.request(method, url, headers=fwd,
                                            data=body or None,
                                            allow_redirects=False) as resp:
                writer.write(
                    f"HTTP/1.1 {resp.status} {resp.reason}\r\n".encode())
                for k, v in resp.headers.items():
                    if k.lower() in ("transfer-encoding", "connection"):
                        continue
                    writer.write(f"{k}: {v}\r\n".encode("latin1"))
                has_len = "Content-Length" in resp.headers
                if not has_len:
                    writer.write(b"Transfer-Encoding: chunked\r\n")
                writer.write(b"Connection: close\r\n\r\n")
                async for chunk in resp.content.iter_chunked(64 * 1024):
                    if not has_len:
                        writer.write(f"{len(chunk):x}\r\n".encode())
                        writer.write(chunk)
                        writer.write(b"\r\n")
                    else:
                        writer.write(chunk)
                    _proxy_bytes.labels("direct").inc(len(chunk))
                    await writer.drain()
                if not has_len:
                    writer.write(b"0\r\n\r\n")
                await writer.drain()
        except Exception as exc:  # noqa: BLE001 - upstream away
            log.debug("direct %s %s failed: %s", method, url, exc)
            try:
                writer.write(b"HTTP/1.1 502 Bad Gateway\r\n\r\n")
                await writer.drain()
            except OSError:
                pass
        return False
