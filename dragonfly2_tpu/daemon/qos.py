"""Multi-tenant QoS governor: class-aware admission with explicit brownout.

The robustness core of the QoS plane (docs/RESILIENCE.md "QoS and graceful
brownout"). Every NEW download task asks the governor for admission with
its (tenant, class); ``critical``/``standard`` work is always admitted and
counted, while ``bulk`` work is subject to the degradation ladder:

  ``normal``   — bulk admitted freely up to ``bulk_active_limit``;
  ``brownout`` — foreground pressure (active critical tasks) or a full
                 bulk gate: new bulk admissions QUEUE (bounded wait) for
                 a slot instead of piling onto the shared resources;
  ``shed``     — the queue wait expired or the queue itself is full: the
                 bulk request is REJECTED NOW with RESOURCE_EXHAUSTED +
                 ``retry_after_ms`` (surfaced as HTTP 429 + Retry-After on
                 the proxy/object-gateway, a coded error on the daemon
                 RPC) — the common/retry.py ladder already honors the
                 hint, so well-behaved clients back off instead of
                 hammering.

Named states are journaled as flight-recorder rung-style ``qos`` events on
the affected task and counted in ``df_qos_*`` metrics, so "why is my bulk
pull slow" is answerable from /debug/qos and dfdiag --qos rather than by
staring at a wedged queue. The governor itself can never deadlock the shed
path: admission for non-bulk classes takes no lock and no await, the bulk
queue is bounded, every waiter carries its own deadline, and release()
always wakes the next LIVE waiter (cancelled futures are skipped, the same
discipline as the upload server's slot queue).
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass

from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import DEFAULT_PRIORITY_CLASS, PRIORITY_CLASSES

log = logging.getLogger("df.flow.qos")

STATES = ("normal", "brownout", "shed")

_qos_state = REGISTRY.gauge(
    "df_qos_state", "current QoS degradation state "
    "(0=normal, 1=brownout, 2=shed)")
_qos_transitions = REGISTRY.counter(
    "df_qos_transitions_total",
    "QoS degradation-state transitions entered", ("state",))
_qos_admitted = REGISTRY.counter(
    "df_qos_admitted_total", "download tasks admitted, by class", ("cls",))
_qos_queued = REGISTRY.counter(
    "df_qos_queued_total",
    "bulk admissions parked at the brownout queue", ("cls",))
_qos_shed = REGISTRY.counter(
    "df_qos_shed_total",
    "admissions rejected with RESOURCE_EXHAUSTED + retry-after",
    ("cls", "reason"))
_qos_active = REGISTRY.gauge(
    "df_qos_active_tasks", "running downloads currently counted by the "
    "QoS governor, by class", ("cls",))


@dataclass
class QosSection:
    """Daemon QoS knobs (DaemonConfig.qos). Defaults keep a classless
    fleet byte-identical to pre-QoS behavior: everything registers as
    ``standard``, which is never queued or shed."""

    enabled: bool = True
    # concurrent bulk downloads admitted before the gate closes
    # (0 = unlimited: brownout still queues on foreground pressure)
    bulk_active_limit: int = 8
    # active critical tasks at which new bulk work browns out even with
    # bulk slots free (foreground pressure signal)
    brownout_critical_threshold: int = 1
    # bounded brownout-queue wait before a bulk admission sheds
    queue_wait_s: float = 5.0
    # queued bulk admissions held at once; beyond this, shed immediately
    queue_limit: int = 64
    # retry-after hint stamped on sheds (the 429 contract)
    shed_retry_after_ms: int = 2000


class QosGovernor:
    """Per-daemon admission governor. One instance per daemon process,
    shared by the RPC server, proxy, and object gateway through
    PeerTaskManager's conductor-creation path."""

    def __init__(self, cfg: QosSection | None = None, *, shaper=None):
        self.cfg = cfg or QosSection()
        self.shaper = shaper              # class_snapshot() for /debug/qos
        self.active: dict[str, int] = {c: 0 for c in PRIORITY_CLASSES}
        self.state = "normal"
        self._waiters: deque = deque()    # (future, enqueued_at)
        self.counters = {
            "admitted": {c: 0 for c in PRIORITY_CLASSES},
            "queued": 0,
            "shed": {c: 0 for c in PRIORITY_CLASSES},
        }
        self.tenant_counters: dict[str, dict] = {}
        self._state_since = time.monotonic()

    # ------------------------------------------------------------------

    def _set_state(self, state: str) -> None:
        if state == self.state:
            return
        log.info("qos state %s -> %s (active=%s queued=%d)", self.state,
                 state, self.active, len(self._waiters))
        self.state = state
        self._state_since = time.monotonic()
        _qos_state.set(STATES.index(state))
        _qos_transitions.labels(state).inc()

    def _pressure(self) -> bool:
        """Foreground pressure: enough active critical work that new bulk
        admissions should queue rather than contend."""
        return (self.active["critical"]
                >= max(self.cfg.brownout_critical_threshold, 1))

    def _bulk_gate_full(self) -> bool:
        limit = self.cfg.bulk_active_limit
        return limit > 0 and self.active["bulk"] >= limit

    def _note_tenant(self, tenant: str, key: str) -> None:
        if not tenant:
            return
        row = self.tenant_counters.setdefault(
            tenant, {"admitted": 0, "queued": 0, "shed": 0})
        row[key] += 1

    def _shed(self, cls: str, tenant: str, reason: str) -> None:
        self.counters["shed"][cls] += 1
        self._note_tenant(tenant, "shed")
        _qos_shed.labels(cls, reason).inc()
        self._set_state("shed")
        exc = DFError(Code.RESOURCE_EXHAUSTED,
                      f"qos: {cls} admission shed ({reason}); retry later")
        # the retry ladder's hint (common/retry.retry_after_s) and the
        # proxy/object-gateway's Retry-After header both read this
        exc.retry_after_ms = self.cfg.shed_retry_after_ms
        raise exc

    # ------------------------------------------------------------------

    async def admit(self, cls: str, tenant: str = "") -> tuple[str, str]:
        """Admit one new download task of ``cls``; returns ``(class,
        ruling)`` where ruling is ``"ok"`` (admitted immediately) or
        ``"queued"`` (admitted after riding the brownout queue — callers
        journal it as a flight ``qos`` event). The class comes back so
        callers pass the exact accounted value to ``release``. Raises
        RESOURCE_EXHAUSTED (+retry_after_ms) on shed. Non-bulk classes
        never block here."""
        if cls not in PRIORITY_CLASSES:
            cls = DEFAULT_PRIORITY_CLASS
        if not self.cfg.enabled or cls != "bulk":
            self._admit_now(cls, tenant)
            return cls, "ok"
        # fresh arrivals queue behind existing waiters (`self._waiters`
        # in the gate): without it a bulk request landing just after
        # pressure receded would jump the FIFO queue while the waiters
        # ride out their deadlines — the same inversion the upload
        # server's slot gate guards against
        if not self._pressure() and not self._bulk_gate_full() \
                and not self._waiters:
            if self.state != "normal":
                self._set_state("normal")
            self._admit_now(cls, tenant)
            return cls, "ok"
        # brownout: queue the admission with a bounded deadline
        if len(self._waiters) >= self.cfg.queue_limit:
            self._shed(cls, tenant, "queue-full")
        self._set_state("brownout")
        self.counters["queued"] += 1
        self._note_tenant(tenant, "queued")
        _qos_queued.labels(cls).inc()
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            await asyncio.wait_for(fut, self.cfg.queue_wait_s)
        except asyncio.TimeoutError:
            self._shed(cls, tenant, "queue-timeout")
        except BaseException:
            # caller died while queued: never strand a granted wake —
            # hand it to the next live waiter (upload-slot discipline)
            if fut.done() and not fut.cancelled():
                self._wake_next()
            else:
                fut.cancel()
            raise
        self._admit_now(cls, tenant)
        return cls, "queued"

    def _admit_now(self, cls: str, tenant: str) -> None:
        self.active[cls] += 1
        self.counters["admitted"][cls] += 1
        self._note_tenant(tenant, "admitted")
        _qos_admitted.labels(cls).inc()
        _qos_active.labels(cls).set(self.active[cls])

    def _wake_next(self) -> bool:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return True
        return False

    def release(self, cls: str) -> None:
        """One admitted task finished (success OR failure — the counter
        must drain either way or the gate wedges shut forever)."""
        if cls not in PRIORITY_CLASSES:
            cls = DEFAULT_PRIORITY_CLASS
        self.active[cls] = max(0, self.active[cls] - 1)
        _qos_active.labels(cls).set(self.active[cls])
        # receding pressure (or a freed bulk slot) wakes AS MANY queued
        # bulk admissions as the gate has headroom for — a critical task
        # finishing with five bulk waiters parked must not drip them out
        # one per release (they would shed on their deadlines while bulk
        # slots sat idle). Each woken admit() re-counts itself via
        # _admit_now, so the wake loop bounds itself by headroom here.
        if self.cfg.enabled and not self._pressure():
            limit = self.cfg.bulk_active_limit
            headroom = (limit - self.active["bulk"]) if limit > 0 \
                else len(self._waiters)
            while headroom > 0 and self._waiters:
                if not self._wake_next():
                    break
                headroom -= 1
        if not self._waiters and self.state != "normal" \
                and not self._pressure() and not self._bulk_gate_full():
            self._set_state("normal")

    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """GET /debug/qos: the whole QoS plane in one read — degradation
        state, per-class active/admitted/queued/shed, per-tenant
        counters, and the shaper's per-class rate grants."""
        out = {
            "state": self.state,
            "state_since_s": round(time.monotonic() - self._state_since, 3),
            "enabled": self.cfg.enabled,
            "active": dict(self.active),
            "queued_now": len(self._waiters),
            "admitted": dict(self.counters["admitted"]),
            "queued_total": self.counters["queued"],
            "shed": dict(self.counters["shed"]),
            "tenants": {t: dict(row)
                        for t, row in self.tenant_counters.items()},
            "limits": {
                "bulk_active_limit": self.cfg.bulk_active_limit,
                "brownout_critical_threshold":
                    self.cfg.brownout_critical_threshold,
                "queue_wait_s": self.cfg.queue_wait_s,
                "queue_limit": self.cfg.queue_limit,
                "shed_retry_after_ms": self.cfg.shed_retry_after_ms,
            },
        }
        if self.shaper is not None:
            out["classes"] = self.shaper.class_snapshot()
        return out


def add_qos_routes(router, governor: QosGovernor) -> None:
    """Mount GET /debug/qos (read-only, ring-free — always on, like
    /debug/health: a browned-out daemon's QoS surface existing only
    behind a debug flag would defeat its purpose)."""
    from aiohttp import web

    async def qos(_request):
        return web.json_response(governor.snapshot())

    router.add_get("/debug/qos", qos)
