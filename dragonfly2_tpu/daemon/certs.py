"""Certificate authority + per-host leaf issuance for HTTPS interception.

Role parity: reference ``client/daemon/proxy/cert.go:37 genLeafCert`` — the
proxy MITMs CONNECT/SNI traffic by minting a short-lived leaf certificate
for the requested host, signed by a CA the fleet's clients trust (containerd
is pointed at the CA file). Differences from the reference, on purpose:

- EC P-256 keys instead of reusing the CA's key material for leaves: leaf
  minting is on the connection path, and EC keygen is ~1ms vs ~100ms RSA.
- The CA auto-generates into the daemon workdir on first use (the reference
  requires an operator-supplied cert; a TPU-pod deployment wants zero-touch
  bootstrap — the same CA file is then mounted into containerd's trust dir).

Leaves live 24h (reference parity) and are cached per host.
"""

from __future__ import annotations

import datetime
import ipaddress
import logging
import os
import re
import ssl
import threading

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

log = logging.getLogger("df.proxy.certs")

LEAF_TTL = datetime.timedelta(hours=24)
CA_TTL = datetime.timedelta(days=3650)


def _name(common_name: str) -> x509.Name:
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])


def generate_ca(common_name: str = "dragonfly2-tpu proxy CA"
                ) -> tuple[bytes, bytes]:
    """Self-signed CA; returns (cert_pem, key_pem)."""
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(_name(common_name))
        .issuer_name(_name(common_name))
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(hours=1))
        .not_valid_after(now + CA_TTL)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_cert_sign=True, crl_sign=True,
            content_commitment=False, key_encipherment=False,
            data_encipherment=False, key_agreement=False,
            encipher_only=False, decipher_only=False), critical=True)
        .sign(key, hashes.SHA256())
    )
    return (cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(serialization.Encoding.PEM,
                              serialization.PrivateFormat.PKCS8,
                              serialization.NoEncryption()))


class CertIssuer:
    """CA-backed leaf minting with a per-host cache.

    ``ca_cert_path``/``ca_key_path`` empty -> auto-generate the CA under
    ``workdir`` (``proxy-ca.crt`` / ``proxy-ca.key``) so operators can point
    clients at the .crt.
    """

    def __init__(self, workdir: str, *, ca_cert_path: str = "",
                 ca_key_path: str = ""):
        self.workdir = workdir
        if not ca_cert_path:
            ca_cert_path = os.path.join(workdir, "proxy-ca.crt")
            ca_key_path = os.path.join(workdir, "proxy-ca.key")
            if not os.path.exists(ca_cert_path):
                os.makedirs(workdir, exist_ok=True)
                cert_pem, key_pem = generate_ca()
                with open(ca_cert_path, "wb") as f:
                    f.write(cert_pem)
                with open(ca_key_path, "wb") as f:
                    f.write(key_pem)
                os.chmod(ca_key_path, 0o600)
                log.info("generated proxy CA at %s", ca_cert_path)
        self.ca_cert_path = ca_cert_path
        self.ca_key_path = ca_key_path or ca_cert_path
        with open(ca_cert_path, "rb") as f:
            self.ca_cert = x509.load_pem_x509_certificate(f.read())
        with open(self.ca_key_path, "rb") as f:
            self.ca_key = serialization.load_pem_private_key(f.read(), None)
        self._lock = threading.Lock()
        # host -> (ssl_ctx, not_after)
        self._cache: dict[str, tuple[ssl.SSLContext, datetime.datetime]] = {}

    def _mint(self, host: str) -> tuple[bytes, bytes, datetime.datetime]:
        key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        not_after = now + LEAF_TTL
        try:
            san: x509.GeneralName = x509.IPAddress(ipaddress.ip_address(host))
        except ValueError:
            san = x509.DNSName(host)
        cert = (
            x509.CertificateBuilder()
            .subject_name(_name(host))
            .issuer_name(self.ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(hours=1))
            .not_valid_after(not_after)
            .add_extension(x509.SubjectAlternativeName([san]), critical=False)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_encipherment=True,
                data_encipherment=True, key_agreement=True,
                content_commitment=False, key_cert_sign=False,
                crl_sign=False, encipher_only=False, decipher_only=False),
                critical=True)
            .sign(self.ca_key, hashes.SHA256())
        )
        return (cert.public_bytes(serialization.Encoding.PEM),
                key.private_bytes(serialization.Encoding.PEM,
                                  serialization.PrivateFormat.PKCS8,
                                  serialization.NoEncryption()),
                not_after)

    def server_context(self, host: str) -> ssl.SSLContext:
        """TLS server context presenting a CA-signed leaf for ``host``."""
        now = datetime.datetime.now(datetime.timezone.utc)
        with self._lock:
            hit = self._cache.get(host)
            if hit is not None and now < hit[1]:
                return hit[0]
        cert_pem, key_pem, not_after = self._mint(host)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        # load_cert_chain wants files; keep them under the workdir tmp.
        # The filename is built from a CLIENT-CONTROLLED host (CONNECT
        # target / raw SNI bytes): strict whitelist sanitization, or a name
        # like '../proxy-ca' would overwrite the CA key itself
        leaf_dir = os.path.join(self.workdir, "leaves")
        os.makedirs(leaf_dir, exist_ok=True)
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", host).strip(".") or "host"
        base = os.path.join(leaf_dir, "leaf-" + safe)
        with open(base + ".crt", "wb") as f:
            f.write(cert_pem + self._ca_pem())
        with open(base + ".key", "wb") as f:
            f.write(key_pem)
        os.chmod(base + ".key", 0o600)
        ctx.load_cert_chain(base + ".crt", base + ".key")
        with self._lock:
            self._cache[host] = (ctx, not_after)
        log.debug("minted leaf cert for %s", host)
        return ctx

    def _ca_pem(self) -> bytes:
        return self.ca_cert.public_bytes(serialization.Encoding.PEM)
