"""Piece dispatcher: picks the next (piece, parent) pair for a worker.

Role parity: reference ``client/daemon/peer/piece_dispatcher.go`` — scores
parents by observed per-byte piece latency with epsilon-random exploration
(``DefaultPieceDispatcherRandomRatio``), so fast ICI-local parents win the
steady state while new parents still get probed.

The dispatcher owns:
  * the queue of pieces still to fetch, each with the set of parents known
    to hold it;
  * per-parent latency EWMAs and failure counts (a parent past the failure
    limit is ejected and its queued pieces re-homed).

Workers call ``get()`` (blocks until a piece is dispatchable or the task is
finished) and then ``report(...)`` with the outcome.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time

from ..idl.messages import PieceInfo

log = logging.getLogger("df.flow.dispatch")

EXPLORE_RATIO = 0.1          # epsilon for random parent choice
PARENT_FAIL_LIMIT = 3        # consecutive failures before ejection
_EWMA_ALPHA = 0.3
BUSY_BACKOFF_S = 0.04        # ~one piece transfer at fan-out rates


class ParentState:
    def __init__(self, peer_id: str, addr: str):
        self.peer_id = peer_id
        self.addr = addr                # "ip:download_port"
        self.ns_per_byte = 0.0          # latency EWMA, 0 = no data yet
        self.consecutive_fails = 0
        self.inflight = 0
        self.ejected = False
        self.busy_until = 0.0           # 503 backpressure: skip until then
        # read by bench.py's engine-state dump (BENCH_DEBUG_DIR)
        self.attempts = 0               # pieces ever dispatched here
        self.announced = 0              # piece announcements received

    def is_busy(self) -> bool:
        return self.busy_until > time.monotonic()

    def observe(self, cost_ms: int, size: int, ok: bool) -> None:
        if ok:
            self.consecutive_fails = 0
            if size > 0:
                sample = cost_ms * 1e6 / size
                if self.ns_per_byte == 0.0:
                    self.ns_per_byte = sample
                else:
                    self.ns_per_byte += _EWMA_ALPHA * (sample - self.ns_per_byte)
        else:
            self.consecutive_fails += 1
            if self.consecutive_fails >= PARENT_FAIL_LIMIT:
                self.ejected = True

    def score(self) -> float:
        """Lower is better. Unprobed parents score best so they get traffic;
        in-flight load scales the expected latency (a parent already serving
        k pieces will deliver the k+1st ~k times slower), which spreads a
        fan-out across parents instead of herding onto the single fastest."""
        if self.ns_per_byte <= 0:
            # still best-in-class, but spread concurrent dispatches across
            # multiple unprobed parents instead of herding onto the first
            return -1.0 + self.inflight * 0.01
        return self.ns_per_byte * (1.0 + self.inflight)


class _PieceState:
    __slots__ = ("info", "holders", "inflight")

    def __init__(self, info: PieceInfo):
        self.info = info
        self.holders: set[str] = set()   # parent peer ids that announced it
        self.inflight = False


class Dispatch:
    """One unit of work handed to a worker."""

    __slots__ = ("piece", "parent")

    def __init__(self, piece: PieceInfo, parent: ParentState):
        self.piece = piece
        self.parent = parent


class PieceDispatcher:
    def __init__(self, *, explore_ratio: float = EXPLORE_RATIO,
                 ordered: bool = False):
        # ordered: fetch lowest-numbered first (stream consumers need early
        # bytes). File tasks use rarest-first instead: a fan-out where every
        # child grabs piece 0,1,2... holds identical sets and has nothing to
        # trade — rarest-first makes siblings complementary sources.
        self.ordered = ordered
        self.explore_ratio = explore_ratio
        self.parents: dict[str, ParentState] = {}
        self._pieces: dict[int, _PieceState] = {}
        self._done: set[int] = set()
        self._closed = False
        self._cond = asyncio.Condition()

    # ------------------------------------------------------------------
    # feeding: parents + announced pieces
    # ------------------------------------------------------------------

    async def add_parent(self, peer_id: str, addr: str, *,
                         resurrect: bool = False) -> ParentState:
        """Known parents keep their state. An ejected parent stays ejected
        unless ``resurrect`` (an explicit scheduler re-assignment) — piece
        announcements must NOT revive a parent the failure limit removed."""
        async with self._cond:
            st = self.parents.get(peer_id)
            if st is None or (st.ejected and resurrect):
                st = ParentState(peer_id, addr)
                self.parents[peer_id] = st
            else:
                st.addr = addr
            self._cond.notify_all()
            return st

    async def remove_parent(self, peer_id: str) -> None:
        async with self._cond:
            st = self.parents.get(peer_id)
            if st is not None:
                st.ejected = True
            # drop it from holder sets too: rarest-first rarity counts must
            # reflect live sources or removed parents skew piece choice
            for ps in self._pieces.values():
                ps.holders.discard(peer_id)
            self._cond.notify_all()

    async def announce(self, parent_id: str, infos: list[PieceInfo]) -> None:
        """Parent reports it holds these pieces."""
        async with self._cond:
            notify = False
            for info in infos:
                if info.piece_num in self._done:
                    continue
                ps = self._pieces.get(info.piece_num)
                if ps is None:
                    ps = _PieceState(info)
                    self._pieces[info.piece_num] = ps
                elif not ps.info.digest and info.digest:
                    ps.info = info
                ps.holders.add(parent_id)
                st = self.parents.get(parent_id)
                if st is not None:
                    st.announced += 1
                notify = True
            if notify:
                self._cond.notify_all()

    async def close(self) -> None:
        async with self._cond:
            self._closed = True
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _live_parents(self) -> list[ParentState]:
        return [p for p in self.parents.values() if not p.ejected]

    def _pick(self) -> Dispatch | None:
        candidates = []
        for ps in self._pieces.values():
            if ps.inflight:
                continue
            holders = [self.parents[h] for h in ps.holders
                       if h in self.parents and not self.parents[h].ejected
                       and not self.parents[h].is_busy()]
            if holders:
                candidates.append((ps, holders))
        if not candidates:
            return None
        if self.ordered:
            ps, holders = min(candidates, key=lambda c: c[0].info.piece_num)
        else:
            # rarest-first with random tie-break
            rarity = min(len(c[1]) for c in candidates)
            ps, holders = random.choice(
                [c for c in candidates if len(c[1]) == rarity])
        if len(holders) > 1 and random.random() < self.explore_ratio:
            parent = random.choice(holders)
        else:
            parent = min(holders, key=ParentState.score)
        ps.inflight = True
        parent.inflight += 1
        parent.attempts += 1
        return Dispatch(ps.info, parent)

    async def get(self, timeout: float | None = None) -> Dispatch | None:
        """Next (piece, parent) to fetch; None when closed or timed out."""
        deadline = time.monotonic() + timeout if timeout else None
        async with self._cond:
            while True:
                if self._closed:
                    return None
                d = self._pick()
                if d is not None:
                    return d
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                # busy parents expire on a clock, not on a notify: poll so a
                # piece whose only holders hit 503 is retried promptly
                if any(p.is_busy() and not p.ejected
                       for p in self.parents.values()):
                    remaining = min(remaining or BUSY_BACKOFF_S,
                                    BUSY_BACKOFF_S)
                try:
                    await asyncio.wait_for(self._cond.wait(), remaining)
                except asyncio.TimeoutError:
                    if deadline is not None and time.monotonic() >= deadline:
                        return None

    async def report_busy(self, d: Dispatch) -> None:
        """Parent answered 503 (upload slots full): not a failure — back off
        that parent briefly and requeue the piece so another holder (or the
        same one, later) serves it."""
        async with self._cond:
            d.parent.inflight = max(0, d.parent.inflight - 1)
            d.parent.busy_until = time.monotonic() + BUSY_BACKOFF_S
            ps = self._pieces.get(d.piece.piece_num)
            if ps is not None:
                ps.inflight = False
            self._cond.notify_all()

    async def report(self, d: Dispatch, *, ok: bool, cost_ms: int = 0) -> None:
        async with self._cond:
            d.parent.inflight = max(0, d.parent.inflight - 1)
            d.parent.observe(cost_ms, d.piece.range_size, ok)
            num = d.piece.piece_num
            if ok:
                self._done.add(num)
                self._pieces.pop(num, None)
            else:
                ps = self._pieces.get(num)
                if ps is not None:
                    ps.inflight = False
                    if d.parent.ejected:
                        ps.holders.discard(d.parent.peer_id)
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def starving(self) -> bool:
        """True when no pending piece has ANY live holder — i.e. more
        announcements are needed. Busy holders don't count as starvation:
        that's backpressure working, and pinging through it would turn
        every 503 into an announcement flood."""
        for ps in self._pieces.values():
            if ps.inflight:
                return False
            for h in ps.holders:
                p = self.parents.get(h)
                if p is not None and not p.ejected:
                    return False
        return True

    def pending_count(self) -> int:
        return len(self._pieces)

    def has_live_parent(self) -> bool:
        return any(not p.ejected for p in self.parents.values())
