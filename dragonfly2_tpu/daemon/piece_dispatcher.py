"""Piece dispatcher: picks the next (piece, parent) pair for a worker.

Role parity: reference ``client/daemon/peer/piece_dispatcher.go`` — scores
parents by observed per-byte piece latency with epsilon-random exploration
(``DefaultPieceDispatcherRandomRatio``), so fast ICI-local parents win the
steady state while new parents still get probed.

The dispatcher owns:
  * the queue of pieces still to fetch, each with the set of parents known
    to hold it;
  * per-parent latency EWMAs and failure counts (a parent past the failure
    limit is ejected and its queued pieces re-homed).

Workers call ``get()`` (blocks until a piece is dispatchable or the task is
finished) and then ``report(...)`` with the outcome.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time

from ..common.metrics import REGISTRY
from ..idl.messages import LinkType, PieceInfo

log = logging.getLogger("df.flow.dispatch")

# locality decision quality, scraped from /metrics by the fake-pod e2e:
# "cross_local_known" = chose a cross-slice parent while a FREE same-slice
# holder was known (only the explore epsilon should ever do this)
_picks = REGISTRY.counter("df_dispatch_pick_total",
                          "parent pick outcomes", ("outcome",))

# Demand-side locality: the scheduler annotates each offered parent with
# the link class it computed from pod topology (PeerAddr.link). Link class
# is a strict TIER in parent choice — any usable ICI holder outranks any
# DCN holder for the same piece. The bandwidth gap between tiers (ICI
# ~TB/s vs 100-400Gbps DCN NICs vs WAN, tpu.topology.LINK_BANDWIDTH_SCORE)
# is larger than any within-tier latency spread, so a scalar cost
# multiplier would let measurement noise invert the ordering exactly when
# links are uncongested. Saturation still escapes the tier: busy (503) and
# cooldown-ejected parents drop out of the holder set, and in-flight load
# shifts choice within the tier.
#
# The tiers name the BANDWIDTH classes the federation plane routes
# around — in the default slice-derived pod mapping they coincide with
# pod boundaries (same-pod beats pod-crossing beats zone-crossing), and
# the ordering is unit-pinned against LINK_BANDWIDTH_SCORE and
# LINK_TIER_NAMES in tests/test_federation.py. Under an explicit
# DF_POD_ID that groups several slices into one pod, an intra-pod DCN
# link still ranks in the DCN tier on purpose: the dispatcher orders by
# where the bytes flow (the NIC), while pod-boundary POLICY stays the
# scheduler's (federation.allows) — the two dimensions agree on
# bandwidth, not on membership:
TIER_SAME_POD = 0    # LOCAL + ICI: the bytes never leave the pod's
                     # wired fabric — ICI moves them at memory-ish rates
TIER_CROSS_POD = 1   # DCN: pod-crossing, the thin tier cross-pod
                     # federation rations through elected pod seeds
TIER_CROSS_ZONE = 2  # WAN: cross-zone / unknown — last resort
LINK_TIER = {
    LinkType.LOCAL: TIER_SAME_POD,
    LinkType.ICI: TIER_SAME_POD,
    LinkType.DCN: TIER_CROSS_POD,
    LinkType.WAN: TIER_CROSS_ZONE,
}

EXPLORE_RATIO = 0.1          # epsilon for random parent choice
PARENT_FAIL_LIMIT = 3        # consecutive failures before ejection
PARENT_FAIL_HARD_LIMIT = 12  # lifetime failures before permanent removal
EJECT_COOLDOWN_S = 4.0       # local ejection is a cooldown, not a divorce
_EWMA_ALPHA = 0.3
BUSY_BACKOFF_S = 0.04        # base 503 backoff (doubles per consecutive busy)
BUSY_BACKOFF_MAX_S = 1.5     # cap on the exponential busy backoff
ENDGAME_RACE_AGE_S = 0.5     # min in-flight age before racing a duplicate


class ParentState:
    """Ejection semantics: a LOCAL failure verdict is a cooldown
    (``EJECT_COOLDOWN_S``), not a divorce — under load spikes a child that
    permanently severs pairs diverges from the scheduler's (stable) view,
    gets no corrective packet, and degenerates to seed-only for the rest of
    the task (the round-4 straggler pathology: one child 100% seed-sourced
    at 8x the swarm's wall-clock). A scheduler prune (``removed``) and the
    lifetime ``PARENT_FAIL_HARD_LIMIT`` stay permanent; the scheduler's
    Z-score bad-node check is the authoritative long-term ejector."""

    def __init__(self, peer_id: str, addr: str, *, is_seed: bool = False,
                 link: LinkType = LinkType.DCN):
        self.peer_id = peer_id
        self.addr = addr                # "ip:download_port"
        self.is_seed = is_seed
        self.link = link
        self.ns_per_byte = 0.0          # latency EWMA, 0 = no data yet
        self.consecutive_fails = 0
        self.total_fails = 0
        self.inflight = 0
        self.removed = False            # permanent (scheduler prune / hard cap)
        self.eject_until = 0.0          # local failure cooldown window
        self.busy_until = 0.0           # 503 backpressure: skip until then
        self.consecutive_busy = 0       # 503s since the last success
        # read by bench.py's engine-state dump (BENCH_DEBUG_DIR)
        self.attempts = 0               # pieces ever dispatched here
        self.announced = 0              # piece announcements received

    @property
    def ejected(self) -> bool:
        """Not usable right now (kept as a property — engine + bench read it)."""
        return self.removed or self.eject_until > time.monotonic()

    def is_busy(self) -> bool:
        return self.busy_until > time.monotonic()

    def observe(self, cost_ms: int, size: int, ok: bool) -> None:
        if ok:
            self.consecutive_fails = 0
            self.consecutive_busy = 0
            if size > 0:
                sample = cost_ms * 1e6 / size
                if self.ns_per_byte == 0.0:
                    self.ns_per_byte = sample
                else:
                    self.ns_per_byte += _EWMA_ALPHA * (sample - self.ns_per_byte)
        else:
            self.consecutive_fails += 1
            self.total_fails += 1
            if self.total_fails >= PARENT_FAIL_HARD_LIMIT:
                self.removed = True
            elif self.consecutive_fails >= PARENT_FAIL_LIMIT:
                self.eject_until = time.monotonic() + EJECT_COOLDOWN_S
                self.consecutive_fails = 0   # fresh chances after cooldown

    def score(self) -> float:
        """Within-class cost, lower is better. Unprobed parents score best
        so they get traffic; in-flight load scales the expected latency (a
        parent already serving k pieces will deliver the k+1st ~k times
        slower), which spreads a fan-out across parents instead of herding
        onto the single fastest."""
        if self.ns_per_byte <= 0:
            return -1.0 + self.inflight * 0.01
        return self.ns_per_byte * (1.0 + self.inflight)

    def rank(self) -> tuple:
        """Full ordering for parent choice: seeds STRICTLY last, then link
        tier, then observed cost (see LINK_TIER rationale). The seed-last
        partition is absolute by design — the seed is the lender of last
        resort (its egress is the scarce resource a fan-out exists to
        conserve), so even a slow mesh peer outranks it; peers that are
        BROKEN rather than slow leave via the failure/cooldown path, and a
        busy-or-dead mesh means the seed still serves immediately."""
        return (1 if self.is_seed else 0,
                LINK_TIER.get(self.link, 1), self.score())


class _PieceState:
    __slots__ = ("info", "holders", "fetching", "first_seen", "dispatched_at")

    def __init__(self, info: PieceInfo):
        self.info = info
        self.holders: set[str] = set()   # parent peer ids that announced it
        self.fetching: set[str] = set()  # parents currently transferring it
        self.first_seen = time.monotonic()
        self.dispatched_at = 0.0         # when the LATEST fetch started

    @property
    def inflight(self) -> bool:
        return bool(self.fetching)


GROUP_LIMIT = 2   # max contiguous pieces per dispatch (one ranged GET)
# Locality grace: a piece whose KNOWN holders are all worse-tier (DCN/WAN/
# seed) is deferred this long after first sight, giving the same-slice
# holder's announcement time to arrive — dispatch-on-first-announcement
# otherwise coin-flips locality (announcement order is a network race, and
# hungry workers grab pieces the moment the first holder appears). Never
# idles a worker: deferred pieces dispatch immediately when nothing
# better-tiered is available.
LOCALITY_GRACE_S = 0.15
# a BUSY same-slice holder is still worth a longer wait than a free DCN
# one (503 backoff is 40ms; DCN costs the whole transfer at ~1/10th the
# bandwidth) — bounded so a stuck local holder can't starve the piece
BUSY_LOCAL_WAIT_S = 1.0
# a BUSY peer holder is worth a short wait before spending SEED egress:
# seed/origin-side bandwidth is the scarce fleet resource (BASELINE
# "% egress saved"), and a freshly idle seed otherwise becomes a magnet
# the moment sibling upload slots saturate (chaos e2e: one survivor took
# half its pieces from a just-restarted seed while busy peers held them)
BUSY_PEER_SEED_WAIT_S = 0.6
ENDGAME_PIECES = 2   # remaining-piece count at which duplicate racing is allowed
# (kept tiny: each duplicate is a full extra transfer — on CPU-bound hosts
# racing the whole tail measurably SLOWS the wave; this is stall insurance
# for the final pieces, not a parallelism strategy)
# Sharded-task swap hold: a swap-class piece (assigned to a co-located
# replica's tree fetch) whose only usable holders are SEEDS waits this
# long for the replica to land + announce it over ICI — pulling it from
# the tree immediately would re-fetch every byte affinity just deduped
# and collapse the disjoint split back into N full pulls. Bounded so a
# dead partner degrades to one extra tree fetch (journaled as a
# ``shard_fallback`` flight event), never a wedge.
SWAP_HOLD_S = 1.5


class Dispatch:
    """One unit of work handed to a worker: one or more CONTIGUOUS pieces
    from one parent, fetched in a single ranged GET. Grouping amortizes the
    per-request cost (HTTP framing, asyncio dispatch, report round-trips)
    that dominates piece transfer on fast links — the same reason the
    back-source path reads piece groups (reference
    ``piece_manager.go:815 concurrentDownloadSourceByPieceGroup``)."""

    __slots__ = ("pieces", "parent")

    def __init__(self, pieces: list[PieceInfo], parent: ParentState):
        self.pieces = pieces
        self.parent = parent

    @property
    def piece(self) -> PieceInfo:   # single-piece convenience (tests, logs)
        return self.pieces[0]

    def size(self) -> int:
        return sum(p.range_size for p in self.pieces)


class PieceDispatcher:
    def __init__(self, *, explore_ratio: float = EXPLORE_RATIO,
                 ordered: bool = False):
        # ordered: fetch lowest-numbered first (stream consumers need early
        # bytes). File tasks use rarest-first instead: a fan-out where every
        # child grabs piece 0,1,2... holds identical sets and has nothing to
        # trade — rarest-first makes siblings complementary sources.
        self.ordered = ordered
        self.explore_ratio = explore_ratio
        self.parents: dict[str, ParentState] = {}
        self._pieces: dict[int, _PieceState] = {}
        self._done: set[int] = set()
        self._closed = False
        self._cond = asyncio.Condition()
        # endgame only when the TASK is nearly done (engine sets this from
        # total_pieces - ready); the local _pieces count is useless as a
        # gate because announcements are drip-fed — a child mid-swarm often
        # knows few undone pieces while hundreds remain
        self.endgame = False
        # structural convoy accounting: cumulative seconds workers spent
        # parked in get() with nothing dispatchable, bucketed by why. The
        # bench reads this to separate "host CPU was the wall" from "the
        # protocol starved its workers" (a wall-clock-only sublinearity
        # number can't tell those apart on a saturated host).
        self.wait_stats = {"no_piece_s": 0.0, "busy_s": 0.0,
                           "seed_busy_s": 0.0, "other_s": 0.0}
        self._seed_hold_expiry: float | None = None   # see _pick seed grace
        # sharded tasks (set_shard_state): pieces this download needs at
        # all (None = every piece) and the swap-class subset held off
        # seed parents for SWAP_HOLD_S
        self.needed: set[int] | None = None
        self.swap_nums: set[int] = set()
        self.swap_hold_s = SWAP_HOLD_S

    # ------------------------------------------------------------------
    # feeding: parents + announced pieces
    # ------------------------------------------------------------------

    async def add_parent(self, peer_id: str, addr: str, *,
                         resurrect: bool = False,
                         is_seed: bool = False,
                         link: LinkType = LinkType.DCN) -> ParentState:
        """Known parents keep their state. An ejected parent stays ejected
        unless ``resurrect`` (an explicit scheduler re-assignment) — piece
        announcements must NOT revive a parent the failure limit removed."""
        if self._closed:     # teardown in progress: don't queue on a lock
            return ParentState(peer_id, addr, is_seed=is_seed, link=link)
        async with self._cond:
            st = self.parents.get(peer_id)
            if st is None or (st.ejected and resurrect):
                fresh = ParentState(peer_id, addr, is_seed=is_seed,
                                    link=link)
                if st is not None:
                    # carry HALVED lifetime failures across resurrection: a
                    # genuinely recovered parent works it off, a persistently
                    # bad one re-trips the hard cap quickly instead of
                    # getting a clean slate each scheduler re-offer
                    fresh.total_fails = st.total_fails // 2
                st = fresh
                self.parents[peer_id] = st
            else:
                st.addr = addr
                st.is_seed = st.is_seed or is_seed
                st.link = link
            self._cond.notify_all()
            return st

    def hard_removed(self, peer_id: str) -> bool:
        """Parent tripped the lifetime failure cap — only an explicit
        scheduler re-assignment may revive it, never the engine's automatic
        sync-stream resurrection."""
        st = self.parents.get(peer_id)
        return (st is not None and st.removed
                and st.total_fails >= PARENT_FAIL_HARD_LIMIT)

    async def remove_parent(self, peer_id: str) -> None:
        if self._closed:
            return
        async with self._cond:
            st = self.parents.get(peer_id)
            if st is not None:
                st.removed = True
            # drop it from holder sets too: rarest-first rarity counts must
            # reflect live sources or removed parents skew piece choice
            for ps in self._pieces.values():
                ps.holders.discard(peer_id)
            self._cond.notify_all()

    async def announce(self, parent_id: str, infos: list[PieceInfo]) -> None:
        """Parent reports it holds these pieces."""
        if self._closed:
            return
        async with self._cond:
            notify = False
            for info in infos:
                if info.piece_num in self._done:
                    continue
                ps = self._pieces.get(info.piece_num)
                if ps is None:
                    ps = _PieceState(info)
                    self._pieces[info.piece_num] = ps
                elif not ps.info.digest and info.digest:
                    ps.info = info
                ps.holders.add(parent_id)
                st = self.parents.get(parent_id)
                if st is not None:
                    st.announced += 1
                notify = True
            if notify:
                self._cond.notify_all()

    def set_shard_state(self, needed: set[int] | None,
                        swap_nums: set[int]) -> None:
        """Sharded-task piece classes (engine.apply_shard_state): pieces
        outside ``needed`` are never dispatched (announcements for them
        are kept — a widen may need them later), ``swap_nums`` wait out
        the swap hold before a seed may serve them. Plain assignment on
        purpose (no cond round): workers re-pick within their bounded
        0.5 s wake cap, and this is called before parents exist on the
        normal path — only a mid-flight widen ever races it, and a widen
        only ADDS dispatchable pieces."""
        self.needed = set(needed) if needed is not None else None
        self.swap_nums = set(swap_nums)

    def _dispatchable(self, num: int) -> bool:
        return self.needed is None or num in self.needed

    async def close(self) -> None:
        # already-closed short-circuit BEFORE touching the lock: teardown
        # calls close() more than once (engine finally + _teardown), and a
        # worker cancelled inside cond.wait can leave the condition lock
        # held by its orphaned waiter (3.10 wait_for+Condition hazard) —
        # the second close must never queue on that lock
        if self._closed:
            return
        self._closed = True       # visible immediately, even if the
        # notify below has to wait for the lock
        async with self._cond:
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def _live_parents(self) -> list[ParentState]:
        return [p for p in self.parents.values() if not p.ejected]

    def _pick(self) -> Dispatch | None:
        now = time.monotonic()
        candidates = []
        deferred = []
        self._seed_hold_expiry = None   # earliest held-piece re-admission
        # locality deferral only exists where locality does: a swarm with
        # no same-slice parents at all (no topology, e.g. plain clusters)
        # must not tax every fresh piece with the grace wait
        any_local = any(not p.is_seed and not p.removed
                        and LINK_TIER.get(p.link, 1) == 0
                        for p in self.parents.values())
        for ps in self._pieces.values():
            if ps.inflight:
                continue
            if not self._dispatchable(ps.info.piece_num):
                continue
            all_states = [self.parents[h] for h in ps.holders
                          if h in self.parents
                          and not self.parents[h].ejected]
            holders = [h for h in all_states if not h.is_busy()]
            if not holders:
                continue
            if (ps.info.piece_num in self.swap_nums
                    and all(h.is_seed for h in holders)):
                # swap-class piece with only the tree to serve it: wait
                # out the swap hold for the owning replica's ICI copy —
                # expiry rides the worker wake scan like the seed grace
                hold_age = now - ps.first_seen
                if hold_age < self.swap_hold_s:
                    expiry = ps.first_seen + self.swap_hold_s
                    if (self._seed_hold_expiry is None
                            or expiry < self._seed_hold_expiry):
                        self._seed_hold_expiry = expiry
                    continue

            def _is_local(h) -> bool:
                return not h.is_seed and LINK_TIER.get(h.link, 1) == 0

            local_free = any(_is_local(h) for h in holders)
            local_busy = any(_is_local(h) for h in all_states)
            age = now - ps.first_seen
            wait = (LOCALITY_GRACE_S if not local_busy
                    else BUSY_LOCAL_WAIT_S)
            if (any_local and not local_free and not self.ordered
                    and age < wait):
                deferred.append((ps, holders))   # see LOCALITY_GRACE_S
            elif (not self.ordered
                  and all(h.is_seed for h in holders)
                  and any(not h.is_seed for h in all_states)
                  and age < BUSY_PEER_SEED_WAIT_S):
                # only FREE holder is a seed but a busy peer holds it: hold
                # the piece back (a REAL wait, not a fallback bias — see
                # BUSY_PEER_SEED_WAIT_S). The worker's wake scan covers
                # both the peer's busy expiry and this piece's age-bound
                # re-admission (_seed_hold_expiry), so nothing can stall.
                expiry = ps.first_seen + BUSY_PEER_SEED_WAIT_S
                if (self._seed_hold_expiry is None
                        or expiry < self._seed_hold_expiry):
                    self._seed_hold_expiry = expiry
                continue
            else:
                candidates.append((ps, holders))
        if not candidates:
            candidates = deferred
        if not candidates:
            return self._pick_endgame()
        if self.ordered:
            ps, holders = min(candidates, key=lambda c: c[0].info.piece_num)
        else:
            # rarest-first; rarity ties (common early in a fan-out) break
            # toward pieces a BEST-LINK-TIER holder can serve, then random —
            # otherwise a child repeatedly picks rare pieces whose only
            # holders sit across the DCN while same-slice supply idles
            def best_tier(c) -> int:
                return min(LINK_TIER.get(h.link, 1) + (3 if h.is_seed else 0)
                           for h in c[1])
            rarity = min(len(c[1]) for c in candidates)
            tied = [c for c in candidates if len(c[1]) == rarity]
            top_tier = min(best_tier(c) for c in tied)
            ps, holders = random.choice(
                [c for c in tied if best_tier(c) == top_tier])
        if len(holders) > 1 and random.random() < self.explore_ratio:
            # exploration probes MESH capacity; the seed's latency is already
            # known territory (and every random pick of it costs scarce
            # origin-side egress)
            peers_only = [h for h in holders if not h.is_seed]
            parent = random.choice(peers_only or holders)
        else:
            parent = min(holders, key=ParentState.rank)
        group = [ps]
        # extend with contiguous pieces the same parent holds, both
        # directions (rarest-first may land mid-run or at a run's end)
        by_start = {p.info.range_start: p for p in self._pieces.values()
                    if not p.inflight}
        by_end = {p.info.range_start + p.info.range_size: p
                  for p in self._pieces.values() if not p.inflight}

        parent_class = (3 if parent.is_seed
                        else LINK_TIER.get(parent.link, 1))

        def usable(cand) -> bool:
            if (cand is None or cand is ps or cand.inflight
                    or parent.peer_id not in cand.holders):
                return False
            if not self._dispatchable(cand.info.piece_num):
                return False
            if parent.is_seed and cand.info.piece_num in self.swap_nums:
                # grouping must not drag a swap-class piece onto the seed
                # past its hold — it dispatches alone once the hold runs
                # out (the journaled fallback path)
                return False
            # don't drag a piece onto a WORSE link than its own best free
            # holder offers — grouping must not bypass the tier preference
            # (and the pick metric) for its groupmates
            best = min((3 if h.is_seed else LINK_TIER.get(h.link, 1))
                       for h in (self.parents[hid] for hid in cand.holders
                                 if hid in self.parents)
                       if not h.ejected and not h.is_busy())
            return parent_class <= best

        while len(group) < GROUP_LIMIT:
            last = group[-1].info
            nxt = by_start.get(last.range_start + last.range_size)
            if not usable(nxt):
                break
            group.append(nxt)
        while len(group) < GROUP_LIMIT:
            head = group[0].info
            prev = by_end.get(head.range_start)
            if not usable(prev):
                break
            group.insert(0, prev)
        now = time.monotonic()
        for g in group:
            g.fetching.add(parent.peer_id)
            g.dispatched_at = now
        parent.inflight += 1
        parent.attempts += len(group)
        if parent.is_seed:
            outcome = "seed"
        elif LINK_TIER.get(parent.link, 1) == 0:
            outcome = "local"
        elif any(not h.is_seed and LINK_TIER.get(h.link, 1) == 0
                 for h in holders):
            outcome = "cross_local_known"
        else:
            outcome = "cross_no_local"
        _picks.labels(outcome).inc(len(group))
        return Dispatch([g.info for g in group], parent)

    def _pick_endgame(self) -> Dispatch | None:
        """Tail latency killer: when only a handful of pieces remain and all
        are already in flight, race a DUPLICATE request from another usable
        holder — the first landing wins, the loser's bytes are discarded
        (landing is idempotent). A slow or stalled parent on the last piece
        otherwise sets the whole wave's wall-clock (BitTorrent's classic
        endgame mode; the reference instead re-requests failed pieces only,
        peertask_conductor.go:1089)."""
        if not self.endgame or not self._pieces:
            return None
        now = time.monotonic()
        for ps in self._pieces.values():
            if not ps.fetching:
                continue   # normal path will take it
            if not self._dispatchable(ps.info.piece_num):
                continue
            # ONE racer per piece, and only against a fetch that has been
            # in flight a while: uncapped immediate racing turns every slow
            # tail piece into a duplicate from every idle worker — bounded
            # waste per piece is one aged duplicate
            if (len(ps.fetching) >= 2
                    or now - ps.dispatched_at < ENDGAME_RACE_AGE_S):
                continue
            alts = [self.parents[h] for h in ps.holders - ps.fetching
                    if h in self.parents and not self.parents[h].ejected
                    and not self.parents[h].is_busy()]
            if ps.info.piece_num in self.swap_nums:
                # endgame racers for a swap-class piece come only from
                # mates: the in-flight fetch IS a live partner serving
                # it, and racing a duplicate onto the SEED would re-fetch
                # over the tree exactly the bytes affinity deduped (and
                # journal a spurious shard_fallback). A wedged mate still
                # exits via the failure/deadline path, after which the
                # normal pick seed-serves past the hold.
                alts = [h for h in alts if not h.is_seed]
            if not alts:
                continue
            parent = min(alts, key=ParentState.rank)
            ps.fetching.add(parent.peer_id)
            ps.dispatched_at = now
            parent.inflight += 1
            parent.attempts += 1
            return Dispatch([ps.info], parent)
        return None

    def _wait_reason(self) -> str:
        """Coarse bucket for why _pick returned None (caller holds _cond):
        no announced pending piece at all, every usable holder backing off
        busy (seed-only vs any), or other (locality deferral, in-flight
        dedup, race-age windows). Classifies parents once (not per piece)
        and short-circuits on the first busy non-seed: this runs on every
        worker wake, which a 503 storm drives at the 0.02s wake floor."""
        if not self._pieces:
            return "no_piece_s"
        busy_ids, busy_seed_ids = set(), set()
        for pid, p in self.parents.items():
            if not p.ejected and p.is_busy():
                (busy_seed_ids if p.is_seed else busy_ids).add(pid)
        if busy_ids or busy_seed_ids:
            for ps in self._pieces.values():
                if ps.inflight:
                    continue
                if ps.holders & busy_ids:
                    return "busy_s"
                if ps.holders & busy_seed_ids:
                    return "seed_busy_s"
        return "other_s"

    async def _notified(self) -> None:
        """One atomic acquire+wait: the lock scope and the cond.wait live
        in a SINGLE coroutine, so when wait_for cancels it the unwind
        releases the lock it re-acquired. The previous shape —
        ``wait_for(self._cond.wait(), t)`` under the caller's ``async
        with`` — split them across two tasks; a worker cancelled while
        parked there orphaned the inner Condition.wait, which re-acquired
        the condition lock in its finally and died HOLDING it. Every later
        acquirer (close(), add_parent, the teardown gather) then queued on
        the poisoned lock forever — the fake-pod silent hang."""
        async with self._cond:
            await self._cond.wait()

    async def get(self, timeout: float | None = None) -> Dispatch | None:
        """Next (piece, parent) to fetch; None when closed or timed out."""
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            async with self._cond:
                if self._closed:
                    return None
                d = self._pick()
                if d is not None:
                    return d
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                # busy/cooldown/race-age windows expire on a clock, not on
                # a notify: wake at the nearest expiry so a piece whose
                # only holders hit 503 (or an eject cooldown, or an endgame
                # race becoming age-eligible) is retried promptly
                now = time.monotonic()
                wake = None
                for p in self.parents.values():
                    if p.removed:
                        continue
                    for until in (p.busy_until, p.eject_until):
                        if until > now:
                            dt = max(until - now, 0.02)
                            wake = dt if wake is None else min(wake, dt)
                if self.endgame:
                    for ps in self._pieces.values():
                        if len(ps.fetching) == 1:
                            until = ps.dispatched_at + ENDGAME_RACE_AGE_S
                            if until > now:
                                dt = max(until - now, 0.02)
                                wake = dt if wake is None else min(wake, dt)
                held = getattr(self, "_seed_hold_expiry", None)
                if held is not None and held > now:
                    dt = max(held - now, 0.02)
                    wake = dt if wake is None else min(wake, dt)
                if wake is not None:
                    remaining = min(remaining or wake, wake)
                reason = self._wait_reason()
            # the wait runs OUTSIDE the pick's lock scope (see _notified):
            # a notify landing in the released gap is missed, which costs
            # at most one `remaining` pause — the loop re-picks after every
            # wake, so correctness only needs the timeout
            t_wait = time.monotonic()
            try:
                # 0.5s cap even for untimed callers: a notify landing in
                # the released gap must cost a bounded re-pick, not a hang
                await asyncio.wait_for(self._notified(),
                                       0.5 if remaining is None else remaining)
            except asyncio.TimeoutError:
                if deadline is not None and time.monotonic() >= deadline:
                    return None
            finally:
                self.wait_stats[reason] += time.monotonic() - t_wait

    async def report_busy(self, d: Dispatch,
                          retry_after_ms: int = 0) -> None:
        """Parent answered 503 (upload slots full): not a failure — back off
        that parent and requeue the pieces so another holder (or the same
        one, later) serves them.

        Backoff sizing is the storm control: with a fixed 40 ms window a
        fan-out whose only early holder is the seed retried it at ~25 Hz per
        child and the 503 round-trips outnumbered real piece downloads
        (r04: 151 busies vs 133 downloads in one 8-child wave). The server's
        measured-transfer-time hint is used when present; otherwise the
        backoff doubles per consecutive busy. Jitter de-synchronizes the
        children so the slot race doesn't re-storm on expiry."""
        if self._closed:
            return
        async with self._cond:
            d.parent.inflight = max(0, d.parent.inflight - 1)
            d.parent.consecutive_busy += 1
            if retry_after_ms > 0:
                backoff = retry_after_ms / 1000.0
            else:
                backoff = min(
                    BUSY_BACKOFF_S * (2 ** (d.parent.consecutive_busy - 1)),
                    BUSY_BACKOFF_MAX_S)
            backoff = min(backoff * random.uniform(0.8, 1.5),
                          BUSY_BACKOFF_MAX_S)
            d.parent.busy_until = time.monotonic() + backoff
            for info in d.pieces:
                ps = self._pieces.get(info.piece_num)
                if ps is not None:
                    ps.fetching.discard(d.parent.peer_id)
            self._cond.notify_all()

    async def report(self, d: Dispatch, *, ok: bool, cost_ms: int = 0,
                     completed: list[int] | None = None) -> None:
        """Outcome of one dispatch. ``completed`` narrows success to a
        subset of the group's piece nums (mid-group digest mismatch);
        ``cost_ms`` covers the whole transfer."""
        if self._closed:
            return
        async with self._cond:
            d.parent.inflight = max(0, d.parent.inflight - 1)
            done_nums = set(completed) if completed is not None else (
                {p.piece_num for p in d.pieces} if ok else set())
            landed = sum(p.range_size for p in d.pieces
                         if p.piece_num in done_nums)
            if done_nums:
                d.parent.observe(cost_ms, landed, True)
            if completed is not None:
                # per-piece verdicts (digest checks): each corrupted piece is
                # a strike — a parent corrupting half its pieces must not
                # launder failures behind its groupmates' successes
                for _ in range(len(d.pieces) - len(done_nums)):
                    d.parent.observe(0, 0, False)
            elif not ok:
                # one failed TRANSFER is one strike, however many pieces
                # happened to ride it
                d.parent.observe(0, 0, False)
            for info in d.pieces:
                num = info.piece_num
                if num in done_nums:
                    self._done.add(num)
                    self._pieces.pop(num, None)
                else:
                    ps = self._pieces.get(num)
                    if ps is not None:
                        ps.fetching.discard(d.parent.peer_id)
                        # drop the holder only on PERMANENT removal: a
                        # cooldown-ejected parent comes back in seconds, and
                        # the per-stream announcement dedup (rpcserver sent
                        # set) means it will never re-announce this piece —
                        # discarding here would orphan the piece meshside
                        if d.parent.removed:
                            ps.holders.discard(d.parent.peer_id)
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def starving(self) -> bool:
        """True when no pending piece has ANY live holder — i.e. more
        announcements are needed. Busy holders don't count as starvation:
        that's backpressure working, and pinging through it would turn
        every 503 into an announcement flood."""
        for ps in self._pieces.values():
            if not self._dispatchable(ps.info.piece_num):
                continue    # unneeded pieces must not mask starvation
            if ps.inflight:
                return False
            for h in ps.holders:
                p = self.parents.get(h)
                if p is not None and not p.ejected:
                    return False
        return True

    def pending_count(self) -> int:
        if self.needed is None:
            return len(self._pieces)
        return sum(1 for n in self._pieces if n in self.needed)

    def has_live_parent(self) -> bool:
        return any(not p.ejected for p in self.parents.values())
