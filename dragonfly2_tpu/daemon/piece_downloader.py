"""Piece downloader: the bulk data path between peers.

Role parity: reference ``client/daemon/peer/piece_downloader.go:165-229`` —
``GET http://{dstAddr}/download/{taskID[:3]}/{taskID}?peerId=`` with a
``Range:`` header against the parent's upload server, verified against the
piece digest announced in the parent's PiecePacket.

One shared aiohttp session with keep-alive connections per daemon: parents
are fetched from many times, so connection reuse is the difference between
one RTT and three per piece.

Zero-stall contract: this module never traverses piece bytes on the event
loop. Bodies stream into POOLED buffers (common/bufpool.py — callers
release them once landed) with only the per-chunk memcpy on-loop; digest
verification happens in the storage landing pass, off-loop, fused with
the write (store.write_span) — hashing each 4-16 MiB piece on the loop
made piece bytes compete with sockets, gossip, and gRPC for the daemon's
one core, and was the dominant term in df_loop_lag_seconds at fan-out.
"""

from __future__ import annotations

import asyncio
import logging
import time

import aiohttp

from ..common import faultgate, tracing
from ..common.bufpool import POOL
from ..common.errors import Code, DFError
from ..idl.messages import PieceInfo

log = logging.getLogger("df.flow.piecedl")


def _classified(code: Code, message: str, fail_code: str) -> DFError:
    """DFError carrying a typed failure verdict (idl.FAIL_CODES): the
    engine forwards ``fail_code`` on the piece report and into the
    per-parent verdict ledger, where the *kind* of failure decides the
    response (corrupt = shun; stall/timeout/refused = congestion-shaped
    backoff only)."""
    err = DFError(code, message)
    err.fail_code = fail_code
    return err


class PieceDownloader:
    def __init__(self, *, timeout_s: float = 30.0, max_connections: int = 64,
                 tls: tuple[str, str, str] | None = None):
        """``tls``: (cert, key, ca) — fleet mTLS material; piece GETs then
        ride https presenting the client leaf."""
        self.timeout_s = timeout_s
        self.max_connections = max_connections
        self.tls = tls
        self._session: aiohttp.ClientSession | None = None

    @property
    def scheme(self) -> str:
        return "https" if self.tls is not None else "http"

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            ssl_ctx = None
            if self.tls is not None:
                import ssl as _ssl
                cert, key, ca = self.tls
                ssl_ctx = _ssl.create_default_context(cafile=ca)
                ssl_ctx.load_cert_chain(cert, key)
                ssl_ctx.check_hostname = False   # peers are dialed by IP;
                # the fleet CA signature is the authentication
                ssl_ctx.verify_mode = _ssl.CERT_REQUIRED
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=self.max_connections,
                                               ssl=ssl_ctx),
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    @staticmethod
    async def _read_body(resp, size: int, what: str,
                         on_first=None, relay_open=None) -> bytearray:
        """Stream the body into ONE pooled buffer. Replaces
        ``resp.read()``: no chunk-list join copy, and — unlike the PR 3/4
        shape — NO digest folding here: hashing a 4-16 MiB piece on the
        loop thread was the per-byte CPU that set the fan-out ceiling on
        core-bound hosts; verification now rides the storage write pass
        off-loop. Only the per-chunk memcpy stays on the loop. The buffer
        comes from the process buffer pool; ownership passes to the
        caller (released back to the pool after landing), and is returned
        to the pool here on every failure path. ``on_first`` fires once
        when the first body chunk lands (flight-recorder ttfb).
        ``relay_open(buf)`` (daemon/relay.py) registers the buffer as an
        in-flight relay span once acquired; the per-chunk watermark
        advance is one attribute store, and a failed read retires the
        span HERE, before the buffer returns to the pool — a relay
        reader must never copy from recycled memory."""
        if faultgate.ARMED:
            # inside the request's timeout window: a 'hang' script parks
            # here until the per-piece deadline cancels the read, exactly
            # like a parent that wedged mid-transfer; 'corrupt' flips a
            # byte BEFORE landing so digest verification trips downstream
            await faultgate.fire("piece.wire", key=what)
        buf = POOL.acquire(size)
        span = relay_open(buf) if relay_open is not None else None
        try:
            mv = memoryview(buf)
            try:
                off = 0
                async for chunk in resp.content.iter_any():
                    if off == 0 and faultgate.ARMED:
                        chunk = faultgate.corrupt("piece.wire", chunk,
                                                  key=what)
                    if off == 0 and on_first is not None:
                        on_first()
                        on_first = None
                    n = len(chunk)
                    if off + n > size:
                        raise _classified(
                            Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                            f"{what}: long read {off + n} > {size}",
                            "stall")
                    mv[off:off + n] = chunk
                    off += n
                    if span is not None:
                        span.advance(off)
                if off != size:
                    raise _classified(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                                      f"{what}: short read {off}/{size}",
                                      "stall")
            finally:
                # drop the export before any release() probes it
                mv.release()
        except BaseException:
            if span is not None:
                span.close()
            POOL.release(buf)
            raise
        return buf

    async def download_piece(self, *, dst_addr: str, task_id: str,
                             src_peer_id: str, piece: PieceInfo,
                             on_first_byte=None, relay_open=None,
                             qos_class: str = "", meta: dict | None = None,
                             ) -> tuple[bytearray, int]:
        """Fetch one piece from a parent. Returns (data, cost_ms); ``data``
        is a POOLED buffer the caller owns (release to ``bufpool.POOL``
        after landing). Bytes are NOT digest-verified here — verification
        happens off-loop in the storage landing pass (the caller treats a
        landing-time mismatch as retry-on-another-parent, same as the
        transport errors raised here as CLIENT_PIECE_DOWNLOAD_FAIL).
        ``qos_class`` rides the GET as ``?cls=`` so the parent's upload
        server can admit the transfer under the right class gate.
        """
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start, size = piece.range_start, piece.range_size
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:   # trace ctx rides the piece request (ref piece_downloader.go:227)
            headers["traceparent"] = tp
        params = {"peerId": src_peer_id}
        if qos_class:
            params["cls"] = qos_class
        what = f"parent {dst_addr} piece {piece.piece_num}"
        t0 = time.monotonic()

        async def fetch():
            async with self._get_session().get(
                    url, headers=headers, params=params) as resp:
                if resp.status == 503:
                    # upload-slot backpressure: the parent is at its
                    # concurrency limit, not broken — the dispatcher reroutes
                    # the piece to another holder or retries after the
                    # parent's measured-transfer-time hint
                    err = DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                    try:
                        err.retry_after_ms = int(
                            resp.headers.get("X-Retry-After-Ms", "0"))
                    except ValueError:
                        err.retry_after_ms = 0
                    raise err
                if resp.status not in (200, 206):
                    raise _classified(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"{what}: HTTP {resp.status}", "refused")
                if meta is not None:
                    # cut-through serve: the parent relayed these bytes
                    # mid-landing — a later corrupt verdict on them is
                    # attributed at reduced weight (see verdicts.record)
                    meta["relayed"] = \
                        resp.headers.get("X-DF-Relay") == "1"
                return await self._read_body(resp, size, what,
                                             on_first=on_first_byte,
                                             relay_open=relay_open)

        try:
            # hard per-piece deadline OUTSIDE aiohttp: the session's total
            # timeout only interrupts aiohttp's own awaits, so a parent (or
            # an injected piece.wire hang) that wedges BETWEEN body reads
            # would stall the worker forever without this
            data = await asyncio.wait_for(fetch(), self.timeout_s)
        except asyncio.TimeoutError:
            raise _classified(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                              f"{what}: per-piece deadline "
                              f"({self.timeout_s:.0f}s)",
                              "timeout") from None
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            # connection-establishment failures never moved a byte
            # ("refused"); anything that died with a request in flight is
            # a mid-transfer stall
            refused = isinstance(exc, (ConnectionRefusedError,
                                       aiohttp.ClientConnectorError))
            raise _classified(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                              f"{what}: {type(exc).__name__}: {exc}",
                              "refused" if refused else "stall") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        return data, cost_ms

    async def download_span(self, *, dst_addr: str, task_id: str,
                            src_peer_id: str, pieces: list[PieceInfo],
                            on_first_byte=None, relay_open=None,
                            qos_class: str = "", meta: dict | None = None,
                            ) -> tuple[bytearray, int]:
        """Fetch CONTIGUOUS pieces in one ranged GET.

        Returns (buf, cost_ms): ONE pooled buffer holding every piece's
        bytes back to back from ``pieces[0].range_start`` — the caller
        owns it (release to ``bufpool.POOL`` after landing). No per-piece
        hashing happens here: verification is fused into the storage
        landing pass (``TaskStorage.write_span``), off the event loop,
        where a digest mismatch drops that piece (the dispatcher requeues
        it) without failing its groupmates. Transport errors raise like
        ``download_piece``.
        """
        if len(pieces) == 1:
            return await self.download_piece(
                dst_addr=dst_addr, task_id=task_id,
                src_peer_id=src_peer_id, piece=pieces[0],
                on_first_byte=on_first_byte, relay_open=relay_open,
                qos_class=qos_class, meta=meta)
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start = pieces[0].range_start
        size = sum(p.range_size for p in pieces)
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:
            headers["traceparent"] = tp
        params = {"peerId": src_peer_id}
        if qos_class:
            params["cls"] = qos_class
        what = f"parent {dst_addr} span @{start}+{size}"
        t0 = time.monotonic()

        async def fetch():
            async with self._get_session().get(
                    url, headers=headers, params=params) as resp:
                if resp.status == 503:
                    err = DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                    try:
                        err.retry_after_ms = int(
                            resp.headers.get("X-Retry-After-Ms", "0"))
                    except ValueError:
                        err.retry_after_ms = 0
                    raise err
                if resp.status not in (200, 206):
                    raise _classified(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"{what}: HTTP {resp.status}", "refused")
                if meta is not None:
                    # cut-through serve: the parent relayed these bytes
                    # mid-landing — a later corrupt verdict on them is
                    # attributed at reduced weight (see verdicts.record)
                    meta["relayed"] = \
                        resp.headers.get("X-DF-Relay") == "1"
                return await self._read_body(resp, size, what,
                                             on_first=on_first_byte,
                                             relay_open=relay_open)

        try:
            # same hard per-span deadline as download_piece (see there)
            data = await asyncio.wait_for(fetch(), self.timeout_s)
        except asyncio.TimeoutError:
            raise _classified(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                              f"{what}: per-piece deadline "
                              f"({self.timeout_s:.0f}s)",
                              "timeout") from None
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            # connection-establishment failures never moved a byte
            # ("refused"); anything that died with a request in flight is
            # a mid-transfer stall
            refused = isinstance(exc, (ConnectionRefusedError,
                                       aiohttp.ClientConnectorError))
            raise _classified(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                              f"{what}: {type(exc).__name__}: {exc}",
                              "refused" if refused else "stall") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        return data, cost_ms
