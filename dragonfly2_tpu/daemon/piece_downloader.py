"""Piece downloader: the bulk data path between peers.

Role parity: reference ``client/daemon/peer/piece_downloader.go:165-229`` —
``GET http://{dstAddr}/download/{taskID[:3]}/{taskID}?peerId=`` with a
``Range:`` header against the parent's upload server, verified against the
piece digest announced in the parent's PiecePacket.

One shared aiohttp session with keep-alive connections per daemon: parents
are fetched from many times, so connection reuse is the difference between
one RTT and three per piece.
"""

from __future__ import annotations

import logging
import time

import aiohttp

from ..common import digest as digestlib
from ..common import tracing
from ..common.errors import Code, DFError
from ..idl.messages import PieceInfo

log = logging.getLogger("df.flow.piecedl")


class PieceDownloader:
    def __init__(self, *, timeout_s: float = 30.0, max_connections: int = 64,
                 tls: tuple[str, str, str] | None = None):
        """``tls``: (cert, key, ca) — fleet mTLS material; piece GETs then
        ride https presenting the client leaf."""
        self.timeout_s = timeout_s
        self.max_connections = max_connections
        self.tls = tls
        self._session: aiohttp.ClientSession | None = None

    @property
    def scheme(self) -> str:
        return "https" if self.tls is not None else "http"

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            ssl_ctx = None
            if self.tls is not None:
                import ssl as _ssl
                cert, key, ca = self.tls
                ssl_ctx = _ssl.create_default_context(cafile=ca)
                ssl_ctx.load_cert_chain(cert, key)
                ssl_ctx.check_hostname = False   # peers are dialed by IP;
                # the fleet CA signature is the authentication
                ssl_ctx.verify_mode = _ssl.CERT_REQUIRED
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=self.max_connections,
                                               ssl=ssl_ctx),
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def download_piece(self, *, dst_addr: str, task_id: str,
                             src_peer_id: str, piece: PieceInfo) -> tuple[bytes, int]:
        """Fetch one piece from a parent. Returns (data, cost_ms).

        Raises CLIENT_PIECE_DOWNLOAD_FAIL on transport/status errors and
        CLIENT_DIGEST_MISMATCH when the bytes do not match the announced
        piece digest (the caller treats both as retry-on-another-parent).
        """
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start, size = piece.range_start, piece.range_size
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:   # trace ctx rides the piece request (ref piece_downloader.go:227)
            headers["traceparent"] = tp
        t0 = time.monotonic()
        try:
            async with self._get_session().get(
                    url, headers=headers,
                    params={"peerId": src_peer_id}) as resp:
                if resp.status == 503:
                    # upload-slot backpressure: the parent is at its
                    # concurrency limit, not broken — the dispatcher reroutes
                    # the piece to another holder or retries shortly
                    raise DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                if resp.status not in (200, 206):
                    raise DFError(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"parent {dst_addr} piece {piece.piece_num}: "
                        f"HTTP {resp.status}")
                data = await resp.read()
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"parent {dst_addr} piece {piece.piece_num}: "
                          f"{type(exc).__name__}: {exc}") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        if len(data) != size:
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"parent {dst_addr} piece {piece.piece_num}: short "
                          f"read {len(data)}/{size}")
        if piece.digest:
            algo, want = digestlib.parse(piece.digest)
            got = digestlib.hash_bytes(algo, data)
            if got != want:
                raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                              f"piece {piece.piece_num} from {dst_addr}: "
                              f"digest mismatch")
        return data, cost_ms

    async def download_span(self, *, dst_addr: str, task_id: str,
                            src_peer_id: str, pieces: list[PieceInfo],
                            ) -> tuple[list[tuple[PieceInfo, bytes]], int]:
        """Fetch CONTIGUOUS pieces in one ranged GET; split + verify each.

        Returns ([(piece, data), ...] for every piece whose digest checked
        out, cost_ms). A digest mismatch drops that piece (the dispatcher
        requeues it) without failing its groupmates. Transport errors raise
        like ``download_piece``.
        """
        if len(pieces) == 1:
            p = pieces[0]
            data, cost = await self.download_piece(
                dst_addr=dst_addr, task_id=task_id,
                src_peer_id=src_peer_id, piece=p)
            return [(p, data)], cost
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start = pieces[0].range_start
        size = sum(p.range_size for p in pieces)
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:
            headers["traceparent"] = tp
        t0 = time.monotonic()
        try:
            async with self._get_session().get(
                    url, headers=headers,
                    params={"peerId": src_peer_id}) as resp:
                if resp.status == 503:
                    raise DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                if resp.status not in (200, 206):
                    raise DFError(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"parent {dst_addr} span @{start}+{size}: "
                        f"HTTP {resp.status}")
                data = await resp.read()
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"parent {dst_addr} span @{start}+{size}: "
                          f"{type(exc).__name__}: {exc}") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        if len(data) != size:
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"parent {dst_addr} span @{start}: short read "
                          f"{len(data)}/{size}")
        out: list[tuple[PieceInfo, bytes]] = []
        view = memoryview(data)
        off = 0
        for p in pieces:
            chunk = view[off:off + p.range_size]
            off += p.range_size
            if p.digest:
                algo, want = digestlib.parse(p.digest)
                if digestlib.hash_bytes(algo, chunk) != want:
                    log.debug("span piece %d from %s: digest mismatch",
                              p.piece_num, dst_addr)
                    continue
            out.append((p, bytes(chunk)))
        return out, cost_ms
