"""Piece downloader: the bulk data path between peers.

Role parity: reference ``client/daemon/peer/piece_downloader.go:165-229`` —
``GET http://{dstAddr}/download/{taskID[:3]}/{taskID}?peerId=`` with a
``Range:`` header against the parent's upload server, verified against the
piece digest announced in the parent's PiecePacket.

One shared aiohttp session with keep-alive connections per daemon: parents
are fetched from many times, so connection reuse is the difference between
one RTT and three per piece.
"""

from __future__ import annotations

import asyncio
import logging
import time

import aiohttp

from ..common import digest as digestlib
from ..common import faultgate, tracing
from ..common.errors import Code, DFError
from ..idl.messages import PieceInfo

log = logging.getLogger("df.flow.piecedl")


class PieceDownloader:
    def __init__(self, *, timeout_s: float = 30.0, max_connections: int = 64,
                 tls: tuple[str, str, str] | None = None):
        """``tls``: (cert, key, ca) — fleet mTLS material; piece GETs then
        ride https presenting the client leaf."""
        self.timeout_s = timeout_s
        self.max_connections = max_connections
        self.tls = tls
        self._session: aiohttp.ClientSession | None = None

    @property
    def scheme(self) -> str:
        return "https" if self.tls is not None else "http"

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            ssl_ctx = None
            if self.tls is not None:
                import ssl as _ssl
                cert, key, ca = self.tls
                ssl_ctx = _ssl.create_default_context(cafile=ca)
                ssl_ctx.load_cert_chain(cert, key)
                ssl_ctx.check_hostname = False   # peers are dialed by IP;
                # the fleet CA signature is the authentication
                ssl_ctx.verify_mode = _ssl.CERT_REQUIRED
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=self.max_connections,
                                               ssl=ssl_ctx),
                timeout=aiohttp.ClientTimeout(total=self.timeout_s))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    @staticmethod
    async def _read_body(resp, size: int, hasher, what: str,
                         on_first=None) -> bytearray:
        """Stream the body into ONE preallocated buffer, folding each
        cache-hot chunk into the digest as it arrives. Replaces
        ``resp.read()``: no chunk-list join copy, and no second cold
        traversal of a 4-16 MiB piece just to hash it — per-byte CPU is
        the fan-out ceiling on core-bound hosts. ``on_first`` fires once
        when the first body chunk lands (flight-recorder ttfb)."""
        if faultgate.ARMED:
            # inside the request's timeout window: a 'hang' script parks
            # here until the per-piece deadline cancels the read, exactly
            # like a parent that wedged mid-transfer; 'corrupt' flips a
            # byte BEFORE hashing so digest verification trips downstream
            await faultgate.fire("piece.wire", key=what)
        buf = bytearray(size)
        mv = memoryview(buf)
        off = 0
        async for chunk in resp.content.iter_any():
            if off == 0 and faultgate.ARMED:
                chunk = faultgate.corrupt("piece.wire", chunk, key=what)
            if off == 0 and on_first is not None:
                on_first()
                on_first = None
            n = len(chunk)
            if off + n > size:
                raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                              f"{what}: long read {off + n} > {size}")
            mv[off:off + n] = chunk
            if hasher is not None:
                hasher.update(chunk)
            off += n
        if off != size:
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"{what}: short read {off}/{size}")
        return buf

    async def download_piece(self, *, dst_addr: str, task_id: str,
                             src_peer_id: str, piece: PieceInfo,
                             on_first_byte=None,
                             ) -> tuple[bytearray, int]:
        """Fetch one piece from a parent. Returns (data, cost_ms).

        Raises CLIENT_PIECE_DOWNLOAD_FAIL on transport/status errors and
        CLIENT_DIGEST_MISMATCH when the bytes do not match the announced
        piece digest (the caller treats both as retry-on-another-parent).
        """
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start, size = piece.range_start, piece.range_size
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:   # trace ctx rides the piece request (ref piece_downloader.go:227)
            headers["traceparent"] = tp
        what = f"parent {dst_addr} piece {piece.piece_num}"
        algo = want = ""
        if piece.digest:
            algo, want = digestlib.parse(piece.digest)
        t0 = time.monotonic()

        async def fetch():
            async with self._get_session().get(
                    url, headers=headers,
                    params={"peerId": src_peer_id}) as resp:
                if resp.status == 503:
                    # upload-slot backpressure: the parent is at its
                    # concurrency limit, not broken — the dispatcher reroutes
                    # the piece to another holder or retries after the
                    # parent's measured-transfer-time hint
                    err = DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                    try:
                        err.retry_after_ms = int(
                            resp.headers.get("X-Retry-After-Ms", "0"))
                    except ValueError:
                        err.retry_after_ms = 0
                    raise err
                if resp.status not in (200, 206):
                    raise DFError(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"{what}: HTTP {resp.status}")
                hasher = digestlib.Hasher(algo) if algo else None
                body = await self._read_body(resp, size, hasher, what,
                                             on_first=on_first_byte)
                return body, hasher

        try:
            # hard per-piece deadline OUTSIDE aiohttp: the session's total
            # timeout only interrupts aiohttp's own awaits, so a parent (or
            # an injected piece.wire hang) that wedges BETWEEN body reads
            # would stall the worker forever without this
            data, hasher = await asyncio.wait_for(fetch(), self.timeout_s)
        except asyncio.TimeoutError:
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"{what}: per-piece deadline "
                          f"({self.timeout_s:.0f}s)") from None
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"{what}: {type(exc).__name__}: {exc}") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        if hasher is not None and hasher.hexdigest() != want:
            raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                          f"piece {piece.piece_num} from {dst_addr}: "
                          f"digest mismatch")
        return data, cost_ms

    async def download_span(self, *, dst_addr: str, task_id: str,
                            src_peer_id: str, pieces: list[PieceInfo],
                            on_first_byte=None,
                            ) -> tuple[list[tuple[PieceInfo, memoryview]], int]:
        """Fetch CONTIGUOUS pieces in one ranged GET; split + verify each.

        Returns ([(piece, data), ...] for every piece whose digest checked
        out, cost_ms) — data items are memoryviews over one shared buffer
        (zero per-piece copies; consumers write them to storage and drop
        them). A digest mismatch drops that piece (the dispatcher requeues
        it) without failing its groupmates. Transport errors raise like
        ``download_piece``.
        """
        if len(pieces) == 1:
            p = pieces[0]
            data, cost = await self.download_piece(
                dst_addr=dst_addr, task_id=task_id,
                src_peer_id=src_peer_id, piece=p,
                on_first_byte=on_first_byte)
            return [(p, memoryview(data))], cost
        url = f"{self.scheme}://{dst_addr}/download/{task_id[:3]}/{task_id}"
        start = pieces[0].range_start
        size = sum(p.range_size for p in pieces)
        headers = {"Range": f"bytes={start}-{start + size - 1}"}
        tp = tracing.traceparent()
        if tp:
            headers["traceparent"] = tp
        what = f"parent {dst_addr} span @{start}+{size}"
        t0 = time.monotonic()

        async def fetch():
            async with self._get_session().get(
                    url, headers=headers,
                    params={"peerId": src_peer_id}) as resp:
                if resp.status == 503:
                    err = DFError(Code.CLIENT_PEER_BUSY,
                                  f"parent {dst_addr} busy")
                    try:
                        err.retry_after_ms = int(
                            resp.headers.get("X-Retry-After-Ms", "0"))
                    except ValueError:
                        err.retry_after_ms = 0
                    raise err
                if resp.status not in (200, 206):
                    raise DFError(
                        Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                        f"{what}: HTTP {resp.status}")
                return await self._read_body(resp, size, None, what,
                                             on_first=on_first_byte)

        try:
            # same hard per-span deadline as download_piece (see there)
            data = await asyncio.wait_for(fetch(), self.timeout_s)
        except asyncio.TimeoutError:
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"{what}: per-piece deadline "
                          f"({self.timeout_s:.0f}s)") from None
        except DFError:
            raise
        except Exception as exc:  # noqa: BLE001 - network boundary
            raise DFError(Code.CLIENT_PIECE_DOWNLOAD_FAIL,
                          f"{what}: {type(exc).__name__}: {exc}") from None
        cost_ms = int((time.monotonic() - t0) * 1000)
        out: list[tuple[PieceInfo, memoryview]] = []
        view = memoryview(data)
        off = 0
        for p in pieces:
            chunk = view[off:off + p.range_size]
            off += p.range_size
            if p.digest:
                algo, want = digestlib.parse(p.digest)
                if digestlib.hash_bytes(algo, chunk) != want:
                    log.debug("span piece %d from %s: digest mismatch",
                              p.piece_num, dst_addr)
                    continue
            out.append((p, chunk))
        return out, cost_ms
