"""PeerTaskConductor: the per-(task, peer) download state machine.

Role parity: reference ``client/daemon/peer/peertask_conductor.go`` — one
conductor per running task in the daemon: registers with the scheduler, pulls
pieces (P2P or back-source), lands them in storage (and optionally straight
into TPU HBM via the DeviceIngest sink), broadcasts progress to subscribers
(file/stream façades), reports results, and finalizes with digest check.

Stage layout: the back-source ladder and storage/sink/subscriber machinery
live here; P2P pulling attaches through ``set_p2p_engine`` (piece_engine.py)
and the scheduler stream through ``scheduler_session.py``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, AsyncIterator

from ..common import digest as digestlib
from ..common.errors import Code, DFError
from ..common.logging import with_fields
from ..common.metrics import REGISTRY
from ..common.piece import Range, compute_piece_size, piece_count
from ..idl.messages import PieceInfo, TaskType, UrlMeta
from ..storage.io_executor import run_io
from ..storage.manager import StorageManager
from ..storage.metadata import TaskMetadata
from ..storage.store import TaskStorage
from . import flight_recorder as fr

log = logging.getLogger("df.core.conductor")

# which landing path served each downloaded span: "native" (fused
# pwrite+crc32c, one traversal), "python" (one pwrite + off-loop hashing),
# or "per_piece" (storage without a span entry point) — the dfbench --pr5
# smoke gate fails when per_piece shows up on the normal P2P path
_span_lands = REGISTRY.counter(
    "df_span_land_total", "downloaded spans landed in storage, by landing "
    "path", ("path",))

# sharded-task delivery (common/sharding.py): per-shard readiness +
# tree-vs-swap byte attribution — the numbers behind "time-to-serving"
_shard_ready = REGISTRY.counter(
    "df_shard_ready_total", "manifest shards whose bytes all verified, "
    "by supply path (tree = this host's assigned fetch subset, swap = "
    "co-located replicas over ICI-near P2P)", ("src",))
_shard_ready_s = REGISTRY.histogram(
    "df_shard_ready_seconds", "time from task start to each shard "
    "becoming ready",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0))
_shard_fallbacks = REGISTRY.counter(
    "df_shard_fallback_total", "swap-class pieces re-pulled from the "
    "tree after the bounded swap hold expired (the ICI swap partner "
    "died or stalled)")
_shard_bytes = REGISTRY.counter(
    "df_shard_bytes_total", "bytes landed into manifest shards, by the "
    "piece's supply class", ("src",))


class PeerTaskConductor:
    # terminal states
    PENDING, RUNNING, SUCCESS, FAILED = "pending", "running", "success", "failed"

    def __init__(self, *, task_id: str, peer_id: str, url: str,
                 url_meta: UrlMeta | None, storage_mgr: StorageManager,
                 piece_mgr: Any, scheduler: Any = None,
                 content_range: Range | None = None,
                 disable_back_source: bool = False,
                 task_type: TaskType = TaskType.STANDARD,
                 device_sink_factory: Any = None,
                 ordered: bool = False,
                 trace: Any = None,
                 flight: Any = None,
                 pex: Any = None,
                 relay: Any = None,
                 shard_manifest: Any = None,
                 requested_shards: list[str] | None = None):
        self.task_id = task_id
        self.peer_id = peer_id
        self.url = url
        self.url_meta = url_meta or UrlMeta()
        # scheduler may refine this at register (application-table lookup);
        # storage GC eviction ordering reads the refined value
        self.resolved_priority = int(self.url_meta.priority)
        # multi-tenant QoS: the service class rides the whole download —
        # shaper registration, piece GETs (upload-slot admission at the
        # parent), storage metadata (class-weighted eviction), the flight
        # summary (per-class SLO budgets) — on EVERY rung including
        # back-source and the scheduler-less pex path, because it lives on
        # the conductor rather than any one session
        from ..idl.messages import resolve_class
        self.qos_class = resolve_class(self.url_meta.qos_class)
        self.tenant = self.url_meta.tenant
        self.storage_mgr = storage_mgr
        self.piece_mgr = piece_mgr
        self.scheduler = scheduler
        self.content_range = content_range
        self.disable_back_source = disable_back_source
        self.task_type = task_type
        self.device_sink_factory = device_sink_factory
        self.ordered = ordered       # stream consumers want low pieces first
        self.trace = trace
        self.flight = flight         # TaskFlight journal (None = disabled)
        self.pex = pex               # PexGossiper (None = plane disabled)
        self.relay = relay           # RelayHub (None = cut-through off)
        self._relay_tracked = False
        # sharded-task delivery (common/sharding.py): the manifest's shard
        # table, the subset this host needs, and — once piece geometry is
        # known (_init_shards) — the tracker that turns verified piece
        # landings into per-shard readiness. Ranged requests keep the
        # whole-file path: a manifest's offsets are content-absolute and
        # a sub-range task's pieces are range-relative.
        shards = getattr(shard_manifest, "shards", shard_manifest)
        self.shard_manifest = (list(shards) if shards
                               and content_range is None
                               and not self.url_meta.range else None)
        self.requested_shards = (list(requested_shards)
                                 if requested_shards else None)
        self.shard_tracker: Any = None
        # piece numbers this download actually needs (None = all): the
        # requested-shard subset's coverage — the dispatcher, back-source
        # hole computation, and the finish check all read this
        self.needed_pieces: set[int] | None = None
        # scheduler shard affinity: the disjoint tree-fetch subset this
        # peer was assigned (RegisterResult.assigned_shards); pieces of
        # every OTHER requested shard are swap-class — held off the seed
        # for a bounded window so co-located replicas supply them over
        # ICI-near P2P (piece_dispatcher swap hold)
        self.affinity_shards: list[str] | None = None
        self.swap_piece_nums: set[int] = set()
        self._swap_shard_names: set[str] = set()
        self._fallback_noted: set[int] = set()
        # completion commit point: set SYNCHRONOUSLY with the final
        # needed-coverage check (engine loop / back-source / finalize) —
        # a widen that loses this race is refused, so a finishing subset
        # task can never be widened into "incomplete" (raising for both
        # requesters) or into a success that silently lacks the
        # joiner's shards
        self._finishing = False
        # True when register failed at the TRANSPORT level (every ring
        # member unreachable) rather than by scheduler verdict — only then
        # may the pex rung second-guess the missing control plane
        self._sched_unreachable = False

        self.state = self.PENDING
        self.fail_code = Code.OK
        self.fail_message = ""
        self.content_length = -1
        self.piece_size = 0
        self.total_pieces = -1
        self.completed_length = 0
        self.traffic_p2p = 0          # bytes from peers (for egress-saved stats)
        self.traffic_source = 0       # bytes from origin
        self.traffic_placed = 0       # bytes placed from the content store
        self._adopted = False         # whole task materialized by digest
        self.start_ms = int(time.time() * 1000)

        # QoS admission release hook (PeerTaskManager): fired exactly once
        # when the run ends, success or failure — an unreleased admission
        # would wedge the bulk gate shut for the rest of the process
        self.qos_release: Any = None
        self.storage: TaskStorage | None = None
        self.device_ingest: Any = None
        self.ready: set[int] = set()          # piece numbers landed
        self._landing: set[int] = set()       # pieces mid-write (dedup race)
        self.done_event = asyncio.Event()
        self._piece_cond = asyncio.Condition()
        self._subscribers: list[asyncio.Queue] = []
        self._run_task: asyncio.Task | None = None
        self._p2p_engine: Any = None
        self._session: Any = None      # scheduler PeerSession once registered
        self.shaper: Any = None
        self.rate_limiter: Any = None  # per-task bucket from the shaper
        self.log = with_fields("df.core.conductor",
                               task=task_id[:12], peer=peer_id[-12:])

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._run_task is None:
            self.state = self.RUNNING
            self._run_task = asyncio.get_running_loop().create_task(self._run())

    def set_p2p_engine(self, engine: Any) -> None:
        self._p2p_engine = engine

    def attach_shaper(self, shaper: Any) -> None:
        self.shaper = shaper
        self.rate_limiter = shaper.register(
            self.task_id, qos_class=self.qos_class, tenant=self.tenant)

    async def _run(self) -> None:
        from ..common import tracing
        with tracing.span("peertask", task_id=self.task_id[:16],
                          peer_id=self.peer_id[-16:], url=self.url) as sp:
            await self._run_traced(sp)

    async def _run_traced(self, sp) -> None:
        try:
            used_p2p = False
            if await self._try_adopt_content():
                # the whole task's bytes were already on disk under another
                # task id (content-digest hit): placed, not transferred —
                # no scheduler, no parents, no origin
                await self._finish_success()
                return
            if self.scheduler is not None:
                self._session = await self._register()
                if self.flight is not None and self._session is not None:
                    self.flight.event(fr.REGISTERED)
                if self._session is not None:
                    assigned = getattr(self._session.result,
                                       "assigned_shards", None)
                    if assigned is not None:
                        self.set_affinity(list(assigned))
                if self._session is not None and self._p2p_engine is not None:
                    if self.flight is not None:
                        self.flight.rung(fr.RUNG_P2P)
                    if self.pex is not None:
                        # opportunistic: swarm-known holders ride an
                        # advisory packet so hot tasks have parents before
                        # the scheduler's assignment lands
                        self.pex.prime(self, self._session)
                    used_p2p = await self._p2p_engine.pull(self, self._session)
            if (not used_p2p and self.pex is not None
                    and (self.scheduler is None or self._sched_unreachable)):
                # the pex rung (docs/RESILIENCE.md): every scheduler is
                # unreachable (or none was ever configured) but gossip
                # knows mesh holders — serve P2P instead of stampeding
                # the origin. Scheduler VERDICTS (NeedBackSource) are
                # respected: this rung only replaces a control plane that
                # is absent, never one that answered.
                used_p2p = await self.pex.try_pull(self)
            if not used_p2p:
                if self.disable_back_source:
                    raise DFError(Code.CLIENT_BACK_SOURCE_ERROR,
                                  "no P2P path and back-source disabled")
                if self.flight is not None:
                    self.flight.rung(fr.RUNG_BACK_SOURCE)
                self.log.info("back-source: %s", self.url)
                await self.piece_mgr.download_source(self)
            await self._finish_success()
        except asyncio.CancelledError:
            await self._finish_fail(Code.CLIENT_CONTEXT_CANCELED, "canceled")
        except DFError as exc:
            await self._finish_fail(exc.code, exc.message)
        except Exception as exc:  # noqa: BLE001
            self.log.exception("task failed")
            await self._finish_fail(Code.UNKNOWN, str(exc))
        finally:
            sp.set(state=self.state, pieces=len(self.ready),
                   traffic_p2p=self.traffic_p2p,
                   traffic_source=self.traffic_source)
            # closed only after finalize so the PeerResult carries the real
            # outcome — a half-pulled peer must never be advertised complete
            if self._session is not None:
                await self._session.close(success=self.state == self.SUCCESS)
            if self.shaper is not None:
                self.shaper.unregister(self.task_id)
            if self.qos_release is not None:
                release, self.qos_release = self.qos_release, None
                release()
            if self._relay_tracked:
                # wakes any streaming serve parked on this task's progress
                # so it winds down now instead of riding out its deadline
                self._relay_tracked = False
                self.relay.untrack(self.task_id)

    async def _register(self):
        """Register with the scheduler; None means "go to origin" (the
        reference's fallback ladder: register-fail / NeedBackSource)."""
        try:
            return await self.scheduler.register(self)
        except DFError as exc:
            if exc.code in (Code.UNAVAILABLE, Code.DEADLINE_EXCEEDED):
                # transport exhaustion, not a verdict: the pex rung may
                # still find mesh parents before origin
                self._sched_unreachable = True
                self.log.info("register unreachable: %s", exc.message)
                return None
            if exc.code == Code.SCHED_NEED_BACK_SOURCE:
                self.log.info("register says back-source: %s", exc.message)
                return None
            raise
        except Exception as exc:  # scheduler unreachable entirely
            self._sched_unreachable = True
            self.log.warning("scheduler unreachable (%s); falling back", exc)
            return None

    def _ingest_to_device(self, num: int, offset: int, data) -> None:
        """Stage one piece into the device sink; a failure disables the
        sink for the rest of the task (best-effort contract). The ONE
        copy of the write/journal/disable sequence — landing, adoption,
        and placement all stage through here."""
        if self.device_ingest is None:
            return
        try:
            self.device_ingest.write(offset, data)
            if self.flight is not None:
                self.flight.event(fr.HBM_DONE, num, nbytes=len(data))
        except Exception:
            self.log.exception("device ingest write failed; disabling sink")
            self.device_ingest.close()
            self.device_ingest = None

    # ------------------------------------------------------------------
    # content-addressed dedupe (storage/castore.py)
    # ------------------------------------------------------------------

    async def _try_adopt_content(self) -> bool:
        """Whole-task dedupe: when the request names a content digest the
        store already holds complete, materialize this task as a hardlink
        of the canonical copy (zero transfers, shared bytes on disk) and
        adopt its piece table. False = no hit; the normal ladder runs."""
        if (not self.url_meta.digest or self.content_range is not None
                or self.url_meta.range
                # url_meta.range is checked SEPARATELY from content_range:
                # a ranged request's content_range is still None here (it
                # resolves against the origin's real total later, in
                # download_source) — adopting on the raw flag alone would
                # materialize the WHOLE file under the ranged task id
                or getattr(self.storage_mgr, "castore", None) is None):
            return False
        md = TaskMetadata(
            task_id=self.task_id, task_type=self.task_type, url=self.url,
            tag=self.url_meta.tag, application=self.url_meta.application,
            digest=self.url_meta.digest, priority=self.resolved_priority,
            qos_class=self.qos_class)
        ts = await run_io(self.storage_mgr.adopt_content, md)
        if ts is None or not (ts.md.done and ts.md.success):
            return False
        self._adopted = True
        self.storage = ts
        self.content_length = ts.md.content_length
        self.piece_size = ts.md.piece_size
        self.total_pieces = ts.md.total_piece_count
        self._init_shards()
        self.storage_mgr.castore.note_hit("content", ts.md.content_length)
        if (self.device_sink_factory is not None
                and self.content_length > 0 and self.device_ingest is None):
            try:
                self.device_ingest = self._make_device_ingest(
                    self.content_length)
            except Exception:  # device sink is best-effort
                self.log.exception("device sink init failed; continuing "
                                   "to disk")
        for num in sorted(ts.md.pieces):
            p = ts.md.pieces[num]
            if self.device_ingest is not None:
                self._ingest_to_device(
                    num, p.start, await run_io(self.storage.read_piece, num))
            async with self._piece_cond:
                self.ready.add(num)
                self.completed_length += p.size
                self._piece_cond.notify_all()
            self.traffic_placed += p.size
            if self.flight is not None:
                self.flight.event(fr.PLACED, num, "cas", p.size)
            self._note_shard_progress(num, p.start, p.size)
            self._publish({"type": "piece", "num": num, "size": p.size,
                           "completed": self.completed_length,
                           "total": self.content_length})
        self.log.info("content dedupe: task adopted from the store "
                      "(%d pieces, %d bytes, zero transferred)",
                      len(ts.md.pieces), self.completed_length)
        return True

    async def place_from_store(self, infos: list[PieceInfo]) -> set[int]:
        """Piece-level dedupe: land any of ``infos`` whose bytes are
        already on disk — recorded under THIS task (warm restart / retry
        over surviving storage) or under any task sharing the digest
        (cross-task placement via the content store) — without touching
        the wire. Returns the piece numbers landed so the engine never
        dispatches a pull for them."""
        if self.storage is None:
            return set()
        castore = getattr(self.storage_mgr, "castore", None)
        placed: set[int] = set()
        reports: list = []
        for info in infos:
            num = info.piece_num
            if num in self.ready or num in self._landing:
                continue
            meta = self.storage.md.pieces.get(num)
            if meta is None and (castore is None or not info.digest
                                 or castore.find_piece(
                                     info.digest, info.range_size,
                                     exclude_task=self.task_id) is None):
                continue
            self._landing.add(num)
            try:
                if meta is not None:
                    # verified at its original landing (or at the boot
                    # re-verify): adopt in place, no copy
                    offset, size, landed = meta.start, meta.size, True
                    if castore is not None:
                        castore.note_hit("task", size)
                else:
                    offset, size = info.range_start, info.range_size
                    landed = await run_io(
                        castore.place_piece, self.storage, num,
                        offset, size, info.digest)
            finally:
                self._landing.discard(num)
            if not landed or num in self.ready:
                continue
            if self.device_ingest is not None:
                self._ingest_to_device(
                    num, offset, await run_io(self.storage.read_piece, num))
            async with self._piece_cond:
                if num in self.ready:
                    continue
                self.ready.add(num)
                self.completed_length += size
                self._piece_cond.notify_all()
            self.traffic_placed += size
            placed.add(num)
            if self.flight is not None:
                self.flight.event(fr.PLACED, num, "cas", size)
            self._note_shard_progress(num, offset, size)
            if self._relay_tracked:
                self.relay.pulse(self.task_id)
            self._publish({"type": "piece", "num": num, "size": size,
                           "completed": self.completed_length,
                           "total": self.content_length})
            if self._session is not None:
                # announce the placement so the scheduler counts this
                # daemon a holder — same shape as a back-source landing
                # (dst ""): the bytes came off no peer's upload slot.
                # Collected and fired CONCURRENTLY below — a warm restart
                # adopts hundreds of pieces, and one sequential RPC round
                # trip per piece would stall the hole-filling download
                # behind pieces x RTT of scheduler chatter
                from ..idl.messages import PieceResult
                now = int(time.time() * 1000)
                reports.append(PieceResult(
                    task_id=self.task_id, src_peer_id=self.peer_id,
                    dst_peer_id="", success=True,
                    piece_info=PieceInfo(piece_num=num, range_start=offset,
                                         range_size=size,
                                         digest=info.digest),
                    begin_ms=now, end_ms=now,
                    finished_count=len(self.ready)))
        if reports:
            await asyncio.gather(*(self._session.report_piece(r)
                                   for r in reports))
        return placed

    # ------------------------------------------------------------------
    # content metadata + piece arrival (called by piece manager / engine)
    # ------------------------------------------------------------------

    # ------------------------------------------------------------------
    # sharded delivery (common/sharding.py)
    # ------------------------------------------------------------------

    def _init_shards(self) -> None:
        """Build the shard tracker once piece geometry is known. A
        malformed manifest demotes the task to the whole-file path (the
        download still completes; nothing becomes a named ready array)."""
        if (self.shard_manifest is None or self.shard_tracker is not None
                or self.piece_size <= 0):
            return
        from ..common import sharding
        try:
            sharding.validate_manifest(self.shard_manifest,
                                       self.content_length)
            tracker = sharding.ShardTracker(self.shard_manifest,
                                            self.requested_shards)
        except ValueError:
            self.log.exception("bad shard manifest; whole-file fallback")
            self.shard_manifest = None
            self.requested_shards = None
            return
        self.shard_tracker = tracker
        if self.flight is not None:
            self.flight.shards_total = tracker.total
        if self.requested_shards is not None and self.total_pieces >= 0:
            self.needed_pieces = tracker.needed_pieces(self.piece_size,
                                                       self.total_pieces)
        self._classify_affinity()
        self.log.info("sharded task: %d/%d shards requested (%s pieces "
                      "needed, %d swap-class)", tracker.total,
                      len(self.shard_manifest),
                      "all" if self.needed_pieces is None
                      else len(self.needed_pieces),
                      len(self.swap_piece_nums))

    def set_affinity(self, names: list[str]) -> None:
        """Scheduler shard-affinity ruling: these requested shards are
        THIS peer's to fetch from the tree; the rest arrive by swap."""
        self.affinity_shards = names
        self._classify_affinity()

    def _classify_affinity(self) -> None:
        tracker = self.shard_tracker
        if tracker is None or self.affinity_shards is None \
                or self.piece_size <= 0:
            return
        from ..common.sharding import pieces_for_shards
        mine = set(self.affinity_shards)
        self._swap_shard_names = {s.name for s in tracker.shards
                                  if s.name not in mine}
        swap_shards = [s for s in tracker.shards
                       if s.name in self._swap_shard_names]
        swap = pieces_for_shards(swap_shards, self.piece_size,
                                 self.total_pieces)
        tree_shards = [s for s in tracker.shards if s.name in mine]
        tree = pieces_for_shards(tree_shards, self.piece_size,
                                 self.total_pieces)
        # a boundary piece shared by a tree shard and a swap shard is
        # tree-class: this host must fetch it anyway, and holding it
        # back would stall the tree shard behind the swap window
        self.swap_piece_nums = swap - tree

    def pieces_remaining(self) -> int:
        """Pieces still to land before this download is DONE — the
        requested-subset count for sharded tasks, total otherwise
        (-1 = unknown geometry)."""
        if self.total_pieces < 0:
            return -1
        if self.needed_pieces is not None:
            return len(self.needed_pieces - self.ready)
        return self.total_pieces - len(self.ready)

    def needed_piece_nums(self, total: int) -> list[int]:
        """Sorted piece numbers this task needs out of ``total`` — the
        back-source hole universe (piece_manager.download_source)."""
        if self.needed_pieces is not None:
            return sorted(n for n in self.needed_pieces if n < total)
        return list(range(total))

    def _note_shard_progress(self, num: int, offset: int, size: int,
                             replay: bool = False) -> None:
        """One verified piece landed: advance shard coverage, journal +
        publish any shard that just completed. Cheap (interval merge) —
        rides every landing path including placements and adoption.
        ``replay`` (the widen path re-feeding already-landed pieces into
        a fresh tracker) skips the byte counters: those bytes were
        counted, with their true tree/swap class, when they landed."""
        tracker = self.shard_tracker
        if tracker is None:
            return
        if not replay:
            # count only the bytes that fall INSIDE tracked shards:
            # manifest-gap pieces (and the non-shard halves of boundary
            # pieces) must not inflate the tree/swap split the metric
            # exists to report
            in_shards = tracker.shard_bytes_in(offset, offset + size)
            if in_shards:
                swap = num in self.swap_piece_nums
                _shard_bytes.labels("swap" if swap else "tree").inc(
                    in_shards)
        t = self.flight.now_ms() if self.flight is not None else 0.0
        for name in tracker.on_span(offset, offset + size, t):
            shard = tracker.shard_for(name)
            src = (fr.SHARD_SRC_SWAP if name in self._swap_shard_names
                   else fr.SHARD_SRC_TREE)
            _shard_ready.labels(fr.SHARD_SRC_NAMES[src]).inc()
            _shard_ready_s.observe(max(t, 0.0) / 1000.0)
            if self.flight is not None:
                self.flight.event(fr.SHARD_READY, src, name,
                                  shard.range_size, t_ms=t)
            self._publish({"type": "shard", "name": name,
                           "src": fr.SHARD_SRC_NAMES[src],
                           "bytes": shard.range_size,
                           "ready": len(tracker.ready),
                           "total": tracker.total})

    def note_shard_fallback(self, num: int, parent_id: str) -> None:
        """A swap-class piece is being served by the TREE after its swap
        hold expired (engine hook): journal it once per piece so dfdiag
        can tell a healthy swap from a died-partner fallback."""
        if num in self._fallback_noted:
            return
        self._fallback_noted.add(num)
        _shard_fallbacks.inc()
        if self.flight is not None:
            self.flight.event(fr.SHARD_FALLBACK, num, parent_id)

    def widen_to_whole_file(self) -> bool:
        """A joiner needs shards (or the whole file) outside this subset
        download: widen to the full piece set mid-flight. Landed coverage
        is replayed into a full-manifest tracker so already-complete
        shards stay ready and partially-covered ones keep their bytes —
        nothing re-fetches. Returns False when this download has already
        COMMITTED to finishing (the engine's/back-source's final
        coverage check, or finalize itself): widening then could fail a
        complete subset as "incomplete" or hand the joiner a success
        missing its shards — the caller starts a fresh conductor over
        the same task storage instead (it adopts the landed pieces and
        fetches only the gap). Runs on the event loop, so the refusal
        check and the mutation are atomic w.r.t. the commit points."""
        if self.requested_shards is None:
            return True
        if self._finishing or self.done_event.is_set():
            return False
        self.log.info("sharded task widened to the whole file by a joiner")
        self.requested_shards = None
        self.needed_pieces = None
        self.swap_piece_nums = set()
        self._swap_shard_names = set()
        if (self.shard_tracker is not None and self.piece_size > 0
                and self.shard_manifest):
            from ..common.sharding import ShardTracker
            fresh = ShardTracker(self.shard_manifest)
            fresh.ready.update(self.shard_tracker.ready)
            self.shard_tracker = fresh
            if self.flight is not None:
                self.flight.shards_total = fresh.total
            if self.storage is not None:
                for num in sorted(self.ready):
                    meta = self.storage.md.pieces.get(num)
                    if meta is not None:
                        self._note_shard_progress(num, meta.start,
                                                  meta.size, replay=True)
        engine = self._p2p_engine
        if engine is not None:
            engine.apply_shard_state(self)
        return True

    def _device_shard_specs(self) -> list[tuple] | None:
        tracker = self.shard_tracker
        if tracker is None:
            return None
        return [(s.name, s.range_start, s.range_size, s.dtype,
                 list(s.shape) if s.shape else None)
                for s in tracker.shards]

    def _make_device_ingest(self, content_length: int):
        specs = self._device_shard_specs()
        if specs:
            return self.device_sink_factory(content_length,
                                            shard_specs=specs)
        return self.device_sink_factory(content_length)

    def set_content_info(self, content_length: int,
                         piece_size: int = 0) -> int:
        """Fix piece geometry; register storage + device sink. Returns the
        piece size. ``content_length`` is the EFFECTIVE length this task
        stores (the sub-range length for ranged tasks — piece offsets are
        range-relative). Safe to call more than once with identical values."""
        if self.piece_size:
            return self.piece_size
        effective_len = content_length
        self.content_length = effective_len
        self.piece_size = piece_size or compute_piece_size(max(effective_len, 0))
        if effective_len >= 0:
            self.total_pieces = piece_count(effective_len, self.piece_size)
        md = TaskMetadata(
            task_id=self.task_id, task_type=self.task_type, url=self.url,
            tag=self.url_meta.tag, application=self.url_meta.application,
            content_length=effective_len, total_piece_count=self.total_pieces,
            piece_size=self.piece_size, digest=self.url_meta.digest,
            priority=self.resolved_priority, qos_class=self.qos_class)
        self.storage = self.storage_mgr.register_task(md)
        self._init_shards()
        if self.relay is not None and not self._relay_tracked:
            # cut-through: from here until finish, the upload server may
            # serve this task's bytes up to the landing watermark
            self._relay_tracked = True
            self.relay.track(self.task_id, total_pieces=self.total_pieces,
                             on_open=self._on_relay_span)
        if (self.device_sink_factory is not None and effective_len > 0
                and self.device_ingest is None):
            try:
                self.device_ingest = self._make_device_ingest(effective_len)
            except Exception:  # device sink is best-effort
                self.log.exception("device sink init failed; continuing to disk")
        return self.piece_size

    def _on_relay_span(self, span) -> None:
        """A new in-flight span opened for this task: publish its piece
        numbers so the rpcserver's sync streams can announce-ahead —
        children may begin pulling these pieces NOW and the upload
        server's streaming path serves them to the watermark."""
        self._publish({"type": "relay",
                       "nums": [p.piece_num for p in span.pieces]})

    async def on_piece_from_source(self, num: int, offset: int, data: bytes,
                                   cost_ms: int) -> None:
        # timestamp taken BEFORE landing (wire_done must precede the
        # hbm_done _land_piece emits), recorded only AFTER the piece
        # verified and landed (a digest-failed or duplicate piece must not
        # count as delivered bytes in the summary); back-source pieces
        # skip the dispatcher stages, so the duration back-dates the start
        t_wire = self.flight.now_ms() if self.flight is not None else 0.0
        if not await self._land_piece(num, offset, data, cost_ms, source=""):
            return
        self.traffic_source += len(data)
        if self.flight is not None:
            self.flight.event(fr.WIRE_DONE, num, fr.ORIGIN, len(data),
                              dur_ms=cost_ms, t_ms=t_wire)
        if self._session is not None:
            # a back-source peer announces its pieces so the scheduler can
            # make it a parent — this is where origin egress gets saved
            from ..idl.messages import PieceInfo, PieceResult
            now = int(time.time() * 1000)
            await self._session.report_piece(PieceResult(
                task_id=self.task_id, src_peer_id=self.peer_id,
                dst_peer_id="", success=True,
                piece_info=PieceInfo(piece_num=num, range_start=offset,
                                     range_size=len(data),
                                     download_cost_ms=cost_ms),
                begin_ms=now - cost_ms, end_ms=now,
                finished_count=len(self.ready)))

    async def on_piece_from_peer(self, num: int, offset: int, data: bytes,
                                 cost_ms: int, parent_id: str,
                                 piece_digest: str = "") -> bool:
        """Returns True when this call landed the piece (the flight
        recorder and traffic stats count only landed pieces). The normal
        P2P path lands through ``on_span_from_peer``; this remains for
        TINY direct-content tasks and per-piece callers."""
        # the downloader no longer hashes on the loop: verification happens
        # in the storage write pass (a mismatch raises DIGEST_MISMATCH)
        landed = await self._land_piece(num, offset, data, cost_ms,
                                        source=parent_id,
                                        piece_digest=piece_digest)
        if landed:
            # endgame-raced duplicates are dropped at landing and must not
            # inflate the traffic accounting (egress-saved stats)
            self.traffic_p2p += len(data)
        return landed

    async def on_span_from_peer(self, parent_id: str,
                                pieces: list[PieceInfo], data,
                                cost_ms_per_piece: int,
                                ) -> tuple[list[int], list[int], list[int]]:
        """Land a whole contiguous downloaded span in ONE pass: one
        storage-executor hop, one buffer traversal (digest verification
        fused with the write — ``TaskStorage.write_span``), one condition
        round for all pieces. This replaces the per-piece landing loop
        that cost a ``to_thread`` hop, a hash pass, and a write per 4-16
        MiB piece.

        ``pieces`` are contiguous ascending; ``data`` holds their bytes
        from ``pieces[0].range_start``. Returns ``(placed, corrupt,
        raced)`` piece-number lists. ``raced`` pieces were CLAIMED BY AN
        IN-FLIGHT RACER (endgame duplicate mid-landing) whose outcome is
        unknown — the caller must report them neither completed nor
        corrupt (the racer's own report settles them); now that
        verification happens at landing, treating a still-landing
        duplicate as done would orphan the piece for good if the racer's
        copy turns out corrupt. Already-LANDED duplicates appear in none
        of the three lists: those verified at landing and are safely
        reportable as complete. The caller owns ``data`` and may release
        it to the buffer pool as soon as this returns: the storage write
        and the HBM staging memcpy have both completed by then (the
        pool's reuse-safety contract).
        """
        if self.storage is None:
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          "span before content info")
        base = pieces[0].range_start
        raced = [p.piece_num for p in pieces
                 if p.piece_num in self._landing]
        claim = [p for p in pieces
                 if p.piece_num not in self.ready
                 and p.piece_num not in self._landing]
        if not claim:
            return [], [], raced
        for p in claim:             # same dedup-race claim as _land_piece
            self._landing.add(p.piece_num)
        try:
            write_span = getattr(self.storage, "write_span", None)
            if write_span is not None:
                spec = [(p.piece_num, p.range_start, p.range_size, p.digest)
                        for p in claim]
                metas, corrupt, path = await run_io(
                    write_span, spec, data, base=base,
                    cost_ms=cost_ms_per_piece, source=parent_id)
                _span_lands.labels(path).inc()
                landed_nums = [m.num for m in metas]
            else:
                # storage without a span entry point (ranged sub-task
                # views): per-piece landing, still off-loop
                _span_lands.labels("per_piece").inc()
                landed_nums, corrupt = [], []
                mv = memoryview(data)
                try:
                    for p in claim:
                        lo = p.range_start - base
                        try:
                            await run_io(
                                self.storage.write_piece, p.piece_num,
                                p.range_start, mv[lo:lo + p.range_size],
                                p.digest, cost_ms=cost_ms_per_piece,
                                source=parent_id)
                        except DFError as exc:
                            if exc.code == Code.CLIENT_DIGEST_MISMATCH:
                                corrupt.append(p.piece_num)
                                continue
                            raise
                        landed_nums.append(p.piece_num)
                finally:
                    mv.release()
        finally:
            for p in claim:
                self._landing.discard(p.piece_num)
        by_num = {p.piece_num: p for p in claim}
        landed_set = set(landed_nums)
        corrupt_set = set(corrupt)
        # claimed pieces that are neither landed nor corrupt were ALREADY
        # on disk: md-recorded by an earlier conductor over this same
        # TaskStorage (retry after a failed download — the ready set died
        # with the old conductor, the storage did not). Their disk bytes
        # were verified when first landed, so count them placed here too;
        # not doing so would report them complete meshside while this
        # conductor never reaches total_pieces — a silent forever-hang.
        on_disk = set(p.piece_num for p in claim
                      if p.piece_num not in landed_set
                      and p.piece_num not in corrupt_set
                      and p.piece_num not in self.ready)
        placed = [n for n in landed_nums if n not in self.ready]
        placed += sorted(on_disk)
        if not placed:
            return [], corrupt, raced
        if self.device_ingest is not None:
            # staging memcpy per landed piece, inline (see _land_piece for
            # why this never rides an executor); the view dies before the
            # caller can recycle the buffer
            view = memoryview(data)
            try:
                for n in placed:
                    p = by_num[n]
                    try:
                        if n in on_disk:
                            # this span's copy of an already-recorded
                            # piece was never digest-checked — stage the
                            # VERIFIED bytes from disk instead
                            src = await run_io(self.storage.read_piece, n)
                            self.device_ingest.write(p.range_start, src)
                        else:
                            lo = p.range_start - base
                            self.device_ingest.write(
                                p.range_start, view[lo:lo + p.range_size])
                        if self.flight is not None:
                            self.flight.event(fr.HBM_DONE, n,
                                              nbytes=p.range_size)
                    except Exception:
                        self.log.exception(
                            "device ingest write failed; disabling sink")
                        self.device_ingest.close()
                        self.device_ingest = None
                        break
            finally:
                view.release()
        events = []
        counted = []
        async with self._piece_cond:
            for n in placed:
                if n in self.ready:
                    # lost a race decided during the awaits above (an
                    # endgame duplicate re-claimed a just-landed piece in
                    # the _landing-discard → ready-add window): the winner
                    # already accounted it — counting twice would inflate
                    # completed_length past content_length
                    continue
                counted.append(n)
                size = by_num[n].range_size
                self.ready.add(n)
                self.completed_length += size
                self.traffic_p2p += size
                if self.shaper is not None:
                    self.shaper.record(self.task_id, size)
                events.append({"type": "piece", "num": n, "size": size,
                               "completed": self.completed_length,
                               "total": self.content_length})
            self._piece_cond.notify_all()
        for n in counted:
            p = by_num[n]
            self._note_shard_progress(n, p.range_start, p.range_size)
        if self._relay_tracked:
            # landed bytes are now disk-covered: move relay readers along
            self.relay.pulse(self.task_id)
        for ev in events:
            self._publish(ev)
        return counted, corrupt, raced

    async def _land_piece(self, num: int, offset: int, data: bytes,
                          cost_ms: int, source: str,
                          piece_digest: str = "",
                          pre_verified: bool = False) -> bool:
        """Returns True when THIS call landed the piece (duplicates from
        endgame racing return False and change nothing)."""
        if self.storage is None:
            raise DFError(Code.CLIENT_STORAGE_ERROR, "piece before content info")
        if num in self.ready or num in self._landing:
            # _landing claims the piece BEFORE the await below: endgame
            # duplicate racers land near-simultaneously, and a ready-only
            # check would let both through (double-counted progress, double
            # device-ingest writes, duplicate scheduler success reports)
            return False
        self._landing.add(num)
        try:
            # hashing+write can take ms at 16MiB — runs on the DEDICATED
            # storage executor (io_executor.py), not the shared default
            # pool, so piece landing never queues behind TLS handshakes
            await run_io(self.storage.write_piece, num, offset,
                         data, piece_digest, cost_ms=cost_ms,
                         source=source, pre_verified=pre_verified)
        finally:
            self._landing.discard(num)
        if num in self.ready:     # lost a race decided elsewhere
            return False
        # write() is a ~1ms memcpy + transfer-queue enqueue — the DMA
        # itself runs on the sink's own thread and is never awaited here.
        # Called inline: routing it through to_thread would queue the
        # memcpy behind multi-ms piece-hashing jobs in the shared executor
        # and serialize ingest with storage writes.
        self._ingest_to_device(num, offset, data)
        if self.shaper is not None:
            self.shaper.record(self.task_id, len(data))
        async with self._piece_cond:
            self.ready.add(num)
            self.completed_length += len(data)
            self._piece_cond.notify_all()
        self._note_shard_progress(num, offset, len(data))
        if self._relay_tracked:
            self.relay.pulse(self.task_id)
        self._publish({"type": "piece", "num": num, "size": len(data),
                       "completed": self.completed_length,
                       "total": self.content_length})
        return True

    def on_source_complete(self, total: int) -> None:
        if self.content_length < 0:
            self.content_length = total
            self.total_pieces = len(self.ready)
            if self.storage is not None:
                self.storage.md.content_length = total
                self.storage.md.total_piece_count = self.total_pieces

    # ------------------------------------------------------------------
    # finalize
    # ------------------------------------------------------------------

    async def _verify_digest(self) -> None:
        if not self.url_meta.digest or self.storage is None:
            return
        if self._adopted:
            # the canonical copy verified this digest when IT completed,
            # and adoption is a hardlink of that same inode — a second
            # full-content hash here would re-pay the cost dedupe removed
            return
        if self.content_range is not None:
            # the digest describes the whole file; a sub-range can't check it
            return
        algo, want = digestlib.parse(self.url_meta.digest)

        def compute() -> str:
            def chunks():
                with open(self.storage.data_path(), "rb") as f:
                    remaining = self.content_length
                    while remaining > 0:
                        b = f.read(min(4 << 20, remaining))
                        if not b:
                            return
                        remaining -= len(b)
                        yield b
            return digestlib.hash_stream(algo, chunks())

        # default executor ON PURPOSE (not run_io): this is a full-content
        # hash — minutes at multi-GB — and the storage pool is 4 threads
        # sized for piece landings; parking it there would queue every
        # in-flight span write behind a finalizing task
        got = await asyncio.to_thread(compute)
        if got != want:
            raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                          f"content digest mismatch: {algo}:{got[:12]}..")

    async def _verify_shard_digests(self) -> None:
        """Optional whole-shard digests (ShardInfo.digest) checked at
        finalize over the landed bytes; per-piece digests already
        verified every piece at landing, so this is belt-and-braces for
        manifests that carry them."""
        tracker = self.shard_tracker
        if tracker is None or self.storage is None:
            return
        to_check = [s for s in tracker.shards
                    if s.digest and s.name in tracker.ready]
        if not to_check:
            return
        path = self.storage.data_path()

        def compute() -> list[str]:
            bad: list[str] = []
            with open(path, "rb") as f:
                for s in to_check:
                    algo, want = digestlib.parse(s.digest)
                    hasher = digestlib.Hasher(algo)
                    f.seek(s.range_start)
                    remaining = s.range_size
                    while remaining > 0:
                        b = f.read(min(4 << 20, remaining))
                        if not b:
                            break
                        remaining -= len(b)
                        hasher.update(b)
                    if remaining or hasher.hexdigest() != want:
                        bad.append(s.name)
            return bad

        # default executor, same rationale as _verify_digest: multi-GB
        # hashing must not queue span landings on the 4-thread storage pool
        bad = await asyncio.to_thread(compute)
        if bad:
            raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                          f"shard digest mismatch: {bad}")

    async def _finish_success(self) -> None:
        # a requested-shard subset finishes when ITS pieces are all in;
        # the task's storage then stays a warm PARTIAL (never marked
        # done): peers see exactly the pieces it holds, a later request
        # for other shards adopts them via place_from_store, and the
        # complete-task reuse path can never serve the partial file as
        # whole content
        self._finishing = True      # widen refused from here on
        subset_done = (self.needed_pieces is not None
                       and self.total_pieces >= 0
                       and len(self.ready) < self.total_pieces
                       and not (self.needed_pieces - self.ready))
        if (self.total_pieces >= 0 and len(self.ready) < self.total_pieces
                and not subset_done):
            raise DFError(Code.CLIENT_STORAGE_ERROR,
                          f"incomplete: {len(self.ready)}/{self.total_pieces} pieces")
        await self._verify_shard_digests()
        if subset_done:
            if self.storage is not None:
                await run_io(self.storage.persist)
        else:
            await self._verify_digest()
            if self.storage is not None:
                await run_io(self.storage.mark_done, success=True,
                             content_length=self.content_length,
                             total_piece_count=self.total_pieces)
        if self.device_ingest is not None:
            try:
                self.device_ingest.flush()   # enqueue-only, non-blocking
            except Exception:
                self.log.exception("device sink flush failed")
                self.device_ingest.close()
                self.device_ingest = None
        if self.device_ingest is not None:
            # inside the peertask span context: the HBM landing joins the
            # task's trace (schedule decision -> piece fetch -> HBM)
            from ..common import tracing
            spans = list(self.device_ingest.transfer_spans)
            with tracing.span("hbm.ingest",
                              task_id=self.task_id[:16]) as hsp:
                hsp.set(transfers=len(spans),
                        done_fraction=self.device_ingest.done_fraction(),
                        dma_ms=round(sum(b - a for a, b in spans) * 1e3, 3))
            if self.flight is not None:
                self.flight.hbm_spans(spans)
        self.state = self.SUCCESS
        if self.flight is not None:
            self.flight.finish(self.SUCCESS)
            # count this task's stage-budget breaches into
            # df_slo_breach_total (once, here — summaries themselves only
            # carry the annotation)
            from ..common.health import PLANE
            PLANE.slo.observe_summary(self.flight.summarize())
        self._publish({"type": "done", "success": True,
                       "completed": self.completed_length,
                       "total": self.content_length})
        self.done_event.set()
        async with self._piece_cond:
            self._piece_cond.notify_all()
        self.log.info("task success: %d bytes, %d pieces (p2p=%d src=%d "
                      "placed=%d)", self.completed_length, len(self.ready),
                      self.traffic_p2p, self.traffic_source,
                      self.traffic_placed)

    async def _finish_fail(self, code: Code, message: str) -> None:
        if self.state in (self.SUCCESS, self.FAILED):
            return
        self.state = self.FAILED
        self.fail_code = code
        self.fail_message = message
        if self.flight is not None:
            # ladder exhausted: the fail rung makes the terminal verdict
            # part of the journal, not just the PeerResult code
            self.flight.rung(fr.RUNG_FAIL)
            self.flight.finish(self.FAILED)
            from ..common.health import PLANE
            PLANE.slo.observe_summary(self.flight.summarize())
        if self.device_ingest is not None:
            self.device_ingest.close()
            self.device_ingest = None
        if self.storage is not None:
            try:
                await run_io(self.storage.mark_done, success=False)
            except Exception:  # noqa: BLE001
                pass
        self._publish({"type": "done", "success": False, "code": int(code),
                       "message": message})
        self.done_event.set()
        async with self._piece_cond:
            self._piece_cond.notify_all()
        self.log.warning("task failed: %s %s", code.name, message)

    async def wait_done(self, timeout: float | None = None) -> bool:
        if timeout:
            try:
                await asyncio.wait_for(self.done_event.wait(), timeout)
            except asyncio.TimeoutError:
                return False
        else:
            await self.done_event.wait()
        return self.state == self.SUCCESS

    def cancel(self) -> None:
        if self._run_task is not None:
            self._run_task.cancel()

    # ------------------------------------------------------------------
    # progress fan-out
    # ------------------------------------------------------------------

    def subscribe(self) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._subscribers.append(q)
        if self.done_event.is_set():
            q.put_nowait({"type": "done", "success": self.state == self.SUCCESS,
                          "code": int(self.fail_code),
                          "completed": self.completed_length,
                          "total": self.content_length,
                          "message": self.fail_message})
        return q

    def unsubscribe(self, q: asyncio.Queue) -> None:
        try:
            self._subscribers.remove(q)
        except ValueError:
            pass

    def _publish(self, event: dict) -> None:
        for q in list(self._subscribers):
            q.put_nowait(event)

    # ------------------------------------------------------------------
    # ordered byte stream (stream tasks, proxy, object gateway)
    # ------------------------------------------------------------------

    async def read_ordered(self) -> AsyncIterator[bytes]:
        """Yield content bytes in order as pieces become ready."""
        num = 0
        while True:
            async with self._piece_cond:
                while (num not in self.ready
                       and not self.done_event.is_set()):
                    await self._piece_cond.wait()
            if num in self.ready:
                assert self.storage is not None
                data = await run_io(self.storage.read_piece, num)
                yield data
                num += 1
                if self.total_pieces >= 0 and num >= self.total_pieces:
                    return
                continue
            # done without the piece -> task ended
            if self.state == self.FAILED:
                raise DFError(self.fail_code or Code.UNKNOWN,
                              self.fail_message or "task failed")
            if self.total_pieces >= 0 and num >= self.total_pieces:
                return
            if self.total_pieces < 0:
                return
