"""Object-storage gateway: S3-ish REST on the daemon, P2P-accelerated GETs.

Role parity: reference ``client/daemon/objectstorage/`` — bucket/object
routes (``objectstorage.go:148-204``), ``getObject`` via the P2P task engine
(:253), ``putObject`` with write-back to the backend (:369). Backends here
are source-client URL bases per bucket (``file://`` — writable, ``http(s)``,
``gs://``, ``memory://`` — read-through), configured in
``ObjectStorageConfig.buckets``; the reference's S3/OSS/OBS SDK clients
collapse into the same scheme registry the download path already uses.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import tempfile
from urllib.parse import quote

from aiohttp import web

from ..common.aiohttp_util import resolve_port
from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import TaskType, UrlMeta
from ..source import SourceRequest, client_for
from .config import ObjectStorageConfig

log = logging.getLogger("df.http.objstore")

_obj_reqs = REGISTRY.counter("df_objstore_requests_total",
                             "object gateway requests", ("op", "status"))


class ObjectGateway:
    def __init__(self, daemon, cfg: ObjectStorageConfig):
        self.daemon = daemon
        self.cfg = cfg
        self.port = cfg.port
        self._runner: web.AppRunner | None = None

    def _object_url(self, bucket: str, key: str) -> str:
        base = self.cfg.buckets.get(bucket)
        if base is None:
            raise DFError(Code.NOT_FOUND, f"bucket {bucket!r} not configured")
        # aiohttp percent-decodes match_info, so a key may arrive as a
        # literal '../..' regardless of how it was escaped on the wire;
        # reject dot segments outright, and for file:// backends verify the
        # resolved path stays under the bucket base.
        if any(seg in ("..", ".") for seg in key.split("/")):
            raise DFError(Code.INVALID_ARGUMENT,
                          f"object key {key!r} contains dot segments")
        url = base.rstrip("/") + "/" + quote(key)
        if url.startswith("file://"):
            root = os.path.realpath(base[len("file://"):])
            dest = os.path.realpath(base[len("file://"):].rstrip("/")
                                    + "/" + key)
            if dest != root and not dest.startswith(root + os.sep):
                raise DFError(Code.INVALID_ARGUMENT,
                              f"object key {key!r} escapes bucket")
        return url

    async def start(self) -> None:
        app = web.Application(client_max_size=0)
        r = app.router
        r.add_get("/healthy", self._healthy)
        r.add_get("/buckets", self._list_buckets)
        r.add_get("/buckets/{bucket}/objects", self._list_objects)
        r.add_head("/buckets/{bucket}/objects/{key:.+}", self._head_object)
        r.add_get("/buckets/{bucket}/objects/{key:.+}", self._get_object,
                  allow_head=False)
        r.add_put("/buckets/{bucket}/objects/{key:.+}", self._put_object)
        r.add_delete("/buckets/{bucket}/objects/{key:.+}", self._delete_object)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.daemon.cfg.listen_ip, self.port)
        await site.start()
        self.port = resolve_port(self._runner)
        log.info("object gateway on :%d (%d buckets)", self.port,
                 len(self.cfg.buckets))

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    # ------------------------------------------------------------------

    async def _healthy(self, _r: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def _list_buckets(self, _r: web.Request) -> web.Response:
        return web.json_response(sorted(self.cfg.buckets))

    async def _list_objects(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        try:
            url = self._object_url(bucket, "")
            entries = await client_for(url).list(SourceRequest(url=url))
        except DFError as exc:
            _obj_reqs.labels("list", "err").inc()
            return web.json_response({"error": exc.message}, status=404)
        _obj_reqs.labels("list", "ok").inc()
        return web.json_response([
            {"key": e.name, "size": e.content_length, "is_dir": e.is_dir}
            for e in entries])

    async def _head_object(self, request: web.Request) -> web.Response:
        try:
            url = self._object_url(request.match_info["bucket"],
                                   request.match_info["key"])
        except DFError:
            _obj_reqs.labels("head", "404").inc()
            return web.Response(status=404)
        try:
            length = await client_for(url).content_length(
                SourceRequest(url=url))
        except DFError:
            length = -1
        if length < 0:
            _obj_reqs.labels("head", "404").inc()
            return web.Response(status=404)
        _obj_reqs.labels("head", "ok").inc()
        return web.Response(headers={"Content-Length": str(length)})

    async def _get_object(self, request: web.Request) -> web.StreamResponse:
        try:
            url = self._object_url(request.match_info["bucket"],
                                   request.match_info["key"])
        except DFError as exc:
            _obj_reqs.labels("get", "404").inc()
            return web.json_response({"error": exc.message}, status=404)
        meta = UrlMeta(tag="objstore")
        try:
            task_id, chunks = await self.daemon.ptm.stream_task(url, meta)
        except DFError as exc:
            _obj_reqs.labels("get", "err").inc()
            return web.json_response({"error": exc.message}, status=502)
        conductor = self.daemon.ptm.conductor(task_id)
        resp = web.StreamResponse()
        length = conductor.content_length if conductor is not None else -1
        if length >= 0:
            resp.content_length = length
        await resp.prepare(request)
        try:
            async for chunk in chunks:
                await resp.write(chunk)
        except DFError as exc:
            # mid-stream failure: the connection drop is the error signal
            log.warning("object stream %s failed: %s", url, exc.message)
            _obj_reqs.labels("get", "err").inc()
            return resp
        await resp.write_eof()
        _obj_reqs.labels("get", "ok").inc()
        return resp

    async def _put_object(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        key = request.match_info["key"]
        try:
            url = self._object_url(bucket, key)
        except DFError as exc:
            _obj_reqs.labels("put", "404").inc()
            return web.json_response({"error": exc.message}, status=404)
        if not url.startswith("file://"):
            _obj_reqs.labels("put", "501").inc()
            return web.json_response(
                {"error": "PUT supported only for file:// backends"},
                status=501)
        dest = url[len("file://"):]
        os.makedirs(os.path.dirname(dest) or "/", exist_ok=True)
        tmp_fd, tmp_path = tempfile.mkstemp(dir=os.path.dirname(dest))
        try:
            with os.fdopen(tmp_fd, "wb") as f:
                async for chunk in request.content.iter_chunked(1 << 20):
                    f.write(chunk)
            os.replace(tmp_path, dest)
        except Exception:
            with open(tmp_path, "ab"):
                pass
            os.unlink(tmp_path)
            raise
        # import into the local cache so peers can fetch it immediately
        # without a second backend read (reference's WriteBack mode)
        try:
            await self.daemon.ptm.import_file(dest, url,
                                              UrlMeta(tag="objstore"),
                                              task_type=TaskType.STANDARD)
        except DFError as exc:
            log.warning("post-PUT import of %s failed: %s", key, exc.message)
        _obj_reqs.labels("put", "ok").inc()
        return web.Response(status=201)

    async def _delete_object(self, request: web.Request) -> web.Response:
        try:
            url = self._object_url(request.match_info["bucket"],
                                   request.match_info["key"])
        except DFError as exc:
            return web.json_response({"error": exc.message}, status=404)
        if url.startswith("file://"):
            try:
                await asyncio.to_thread(os.unlink, url[len("file://"):])
            except FileNotFoundError:
                pass
        # drop the cached task too
        task_id = self.daemon.ptm._task_id(url, UrlMeta(tag="objstore"))
        await self.daemon.ptm.delete_task(task_id)
        _obj_reqs.labels("delete", "ok").inc()
        return web.Response(status=204)
