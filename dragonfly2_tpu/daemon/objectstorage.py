"""Object-storage gateway: S3-ish REST on the daemon, P2P-accelerated GETs.

Role parity: reference ``client/daemon/objectstorage/`` — bucket/object
routes (``objectstorage.go:148-204``), ``getObject`` via the P2P task engine
(:253), ``putObject`` with write-back to the backend (:369). Backends here
are source-client URL bases per bucket (``file://`` — writable, ``http(s)``,
``gs://``, ``memory://`` — read-through), configured in
``ObjectStorageConfig.buckets``; the reference's S3/OSS/OBS SDK clients
collapse into the same scheme registry the download path already uses.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import tempfile
from urllib.parse import quote

from aiohttp import web

from ..common.aiohttp_util import resolve_port
from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..idl.messages import TaskType, UrlMeta
from ..source import SourceRequest, client_for
from .config import ObjectStorageConfig

log = logging.getLogger("df.http.objstore")

_obj_reqs = REGISTRY.counter("df_objstore_requests_total",
                             "object gateway requests", ("op", "status"))


class ObjectGateway:
    def __init__(self, daemon, cfg: ObjectStorageConfig):
        self.daemon = daemon
        self.cfg = cfg
        self.port = cfg.port
        self._runner: web.AppRunner | None = None
        # write-path backends (reference pkg/objectstorage clients);
        # file:// read buckets get an implicit file backend
        from ..common.objectstorage import BackendConfig, make_backend
        self._backends = {}
        for bucket, bcfg in (cfg.backends or {}).items():
            self._backends[bucket] = make_backend(BackendConfig(**bcfg))
        for bucket, base in cfg.buckets.items():
            if bucket not in self._backends and base.startswith("file://"):
                self._backends[bucket] = make_backend(BackendConfig(
                    kind="file", base=base[len("file://"):]))
        self._writebacks: set[asyncio.Task] = set()
        # reads of s3-backed buckets must use the backend's credentials
        # (the s3 source client is a process singleton; one credential set
        # per process — matching the env-var model it replaces)
        s3_creds = {(b["access_key"], b["secret_key"],
                     b.get("region", "us-east-1"))
                    for b in (cfg.backends or {}).values()
                    if b.get("kind") == "s3" and b.get("access_key")}
        if len(s3_creds) > 1:
            # one credential set per process (the source client is a
            # singleton): silently signing bucket B's reads with bucket A's
            # key yields 403s only at read time — fail loudly at config time
            raise DFError(Code.INVALID_ARGUMENT,
                          "multiple s3 backends with DIFFERENT credentials "
                          "are not supported in one daemon")
        if s3_creds:
            from ..common.objectstorage import S3Credentials
            from ..source.client import client_for
            client_for("s3://x/x").set_credentials(
                S3Credentials(*next(iter(s3_creds))))

    def _object_url(self, bucket: str, key: str) -> str:
        base = self.cfg.buckets.get(bucket)
        if base is None:
            raise DFError(Code.NOT_FOUND, f"bucket {bucket!r} not configured")
        bcfg = (self.cfg.backends or {}).get(bucket)
        if base.startswith("s3://") and bcfg and bcfg.get("kind") == "s3":
            # tie the READ path to the configured backend endpoint/bucket:
            # resolving s3:// from process env while writes go to the
            # configured endpoint would 404 after a cache loss (divergent
            # worlds). s3+http(s):// carries the endpoint in the URL.
            endpoint = bcfg["base"].rstrip("/")
            scheme = "s3+https" if endpoint.startswith("https") else "s3+http"
            host = endpoint.split("://", 1)[1]
            backend_bucket = bcfg.get("bucket") or bucket
            base = f"{scheme}://{host}/{backend_bucket}"
        # aiohttp percent-decodes match_info, so a key may arrive as a
        # literal '../..' regardless of how it was escaped on the wire;
        # reject dot segments outright, and for file:// backends verify the
        # resolved path stays under the bucket base.
        if any(seg in ("..", ".") for seg in key.split("/")):
            raise DFError(Code.INVALID_ARGUMENT,
                          f"object key {key!r} contains dot segments")
        url = base.rstrip("/") + "/" + quote(key)
        if url.startswith("file://"):
            # dflint: disable=DF001 — two lstat walks for sandbox containment, µs-scale
            root = os.path.realpath(base[len("file://"):])
            # dflint: disable=DF001 — two lstat walks for sandbox containment, µs-scale
            dest = os.path.realpath(base[len("file://"):].rstrip("/")
                                    + "/" + key)
            if dest != root and not dest.startswith(root + os.sep):
                raise DFError(Code.INVALID_ARGUMENT,
                              f"object key {key!r} escapes bucket")
        return url

    async def start(self) -> None:
        app = web.Application(client_max_size=0)
        r = app.router
        r.add_get("/healthy", self._healthy)
        r.add_get("/buckets", self._list_buckets)
        r.add_get("/buckets/{bucket}/objects", self._list_objects)
        r.add_head("/buckets/{bucket}/objects/{key:.+}", self._head_object)
        r.add_get("/buckets/{bucket}/objects/{key:.+}", self._get_object,
                  allow_head=False)
        r.add_put("/buckets/{bucket}/objects/{key:.+}", self._put_object)
        r.add_delete("/buckets/{bucket}/objects/{key:.+}", self._delete_object)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.daemon.cfg.listen_ip, self.port)
        await site.start()
        self.port = resolve_port(self._runner)
        log.info("object gateway on :%d (%d buckets)", self.port,
                 len(self.cfg.buckets))

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        # drain in-flight async write-backs: a 202 promised eventual
        # backend durability — cancelling them on shutdown silently loses
        # the only durable copy
        if self._writebacks:
            log.info("draining %d async write-backs", len(self._writebacks))
            done, pending = await asyncio.wait(self._writebacks, timeout=30)
            for t in pending:
                t.cancel()
                log.error("async write-back cancelled at shutdown — object "
                          "may exist only in the cache")
        for backend in self._backends.values():
            close = getattr(backend, "close", None)
            if close is not None:
                await close()

    # ------------------------------------------------------------------

    async def _healthy(self, _r: web.Request) -> web.Response:
        return web.Response(text="ok")

    async def _list_buckets(self, _r: web.Request) -> web.Response:
        return web.json_response(sorted(self.cfg.buckets))

    async def _list_objects(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        try:
            url = self._object_url(bucket, "")
            entries = await client_for(url).list(SourceRequest(url=url))
        except DFError as exc:
            _obj_reqs.labels("list", "err").inc()
            return web.json_response({"error": exc.message}, status=404)
        _obj_reqs.labels("list", "ok").inc()
        return web.json_response([
            {"key": e.name, "size": e.content_length, "is_dir": e.is_dir}
            for e in entries])

    async def _head_object(self, request: web.Request) -> web.Response:
        try:
            url = self._object_url(request.match_info["bucket"],
                                   request.match_info["key"])
        except DFError:
            _obj_reqs.labels("head", "404").inc()
            return web.Response(status=404)
        try:
            length = await client_for(url).content_length(
                SourceRequest(url=url))
        except DFError:
            length = -1
        if length < 0:
            _obj_reqs.labels("head", "404").inc()
            return web.Response(status=404)
        _obj_reqs.labels("head", "ok").inc()
        return web.Response(headers={"Content-Length": str(length)})

    async def _get_object(self, request: web.Request) -> web.StreamResponse:
        try:
            url = self._object_url(request.match_info["bucket"],
                                   request.match_info["key"])
        except DFError as exc:
            _obj_reqs.labels("get", "404").inc()
            return web.json_response({"error": exc.message}, status=404)
        # multi-tenant QoS: class + tenant ride request headers, same
        # contract as the proxy surface
        meta = UrlMeta(
            tag="objstore",
            tenant=request.headers.get("X-Dragonfly-Tenant", ""),
            qos_class=request.headers.get("X-Dragonfly-Class", ""))
        try:
            task_id, chunks = await self.daemon.ptm.stream_task(url, meta)
        except DFError as exc:
            if exc.code == Code.RESOURCE_EXHAUSTED:
                # QoS shed / tenant quota: the 429 + Retry-After contract
                _obj_reqs.labels("get", "shed").inc()
                retry_ms = getattr(exc, "retry_after_ms", 0) or 1000
                return web.json_response(
                    {"error": exc.message}, status=429,
                    headers={"Retry-After": str(-(-retry_ms // 1000)),
                             "X-Retry-After-Ms": str(retry_ms)})
            _obj_reqs.labels("get", "err").inc()
            return web.json_response({"error": exc.message}, status=502)
        conductor = self.daemon.ptm.conductor(task_id)
        resp = web.StreamResponse()
        length = conductor.content_length if conductor is not None else -1
        if length >= 0:
            resp.content_length = length
        await resp.prepare(request)
        try:
            async for chunk in chunks:
                await resp.write(chunk)
        except DFError as exc:
            # mid-stream failure: the connection drop is the error signal
            log.warning("object stream %s failed: %s", url, exc.message)
            _obj_reqs.labels("get", "err").inc()
            return resp
        await resp.write_eof()
        _obj_reqs.labels("get", "ok").inc()
        return resp

    async def _put_object(self, request: web.Request) -> web.Response:
        """PUT with write-back replication (reference
        ``objectstorage.go:369`` modes):

        - ``write_back`` (default): spool, write to the BACKEND, then
          import into the local piece cache — 201 only after the backend
          durably has the object;
        - ``async_write_back``: 202 as soon as the local import lands, the
          backend upload continues in the background (latency over
          durability; a failed background upload is logged + counted as
          put/writeback_err).
        """
        bucket = request.match_info["bucket"]
        key = request.match_info["key"]
        try:
            url = self._object_url(bucket, key)
        except DFError as exc:
            _obj_reqs.labels("put", "404").inc()
            return web.json_response({"error": exc.message}, status=404)
        backend = self._backends.get(bucket)
        if backend is None:
            _obj_reqs.labels("put", "501").inc()
            return web.json_response(
                {"error": f"bucket {bucket!r} has no write backend"},
                status=501)
        mode = (request.headers.get("X-Dragonfly-Write-Back-Mode")
                or request.query.get("mode") or "write_back")
        if mode not in ("write_back", "async_write_back"):
            _obj_reqs.labels("put", "400").inc()
            return web.json_response({"error": f"unknown mode {mode!r}"},
                                     status=400)
        # spool the body once; both the local import and the backend
        # upload read from the spool
        tmp_fd, tmp_path = tempfile.mkstemp(prefix="df-objput-")
        try:
            with os.fdopen(tmp_fd, "wb") as f:
                async for chunk in request.content.iter_chunked(1 << 20):
                    await asyncio.to_thread(f.write, chunk)

            async def import_local() -> None:
                # a re-PUT of an existing key must replace the cached task,
                # or the mesh serves the OLD bytes while the backend holds
                # the new ones (import_file no-ops on existing task ids)
                task_id = self.daemon.ptm._task_id(url,
                                                   UrlMeta(tag="objstore"))
                try:
                    await self.daemon.ptm.delete_task(task_id)
                except DFError:
                    pass
                await self.daemon.ptm.import_file(
                    tmp_path, url, UrlMeta(tag="objstore"),
                    task_type=TaskType.STANDARD)

            async def write_back() -> None:
                # dflint: disable=DF001 — one stat of a temp file we just wrote
                size = os.path.getsize(tmp_path)

                async def chunks():
                    # off-loop open AND reads: a multi-GB upload must not
                    # stall the daemon's sockets per block
                    f = await asyncio.to_thread(open, tmp_path, "rb")
                    try:
                        while True:
                            block = await asyncio.to_thread(f.read, 1 << 20)
                            if not block:
                                return
                            yield block
                    finally:
                        f.close()

                backend_bucket = getattr(backend, "bucket", "") or bucket
                await backend.put_object(backend_bucket, key, chunks(),
                                         content_length=size)

            if mode == "write_back":
                # backend FIRST: 201 promises the origin has the object,
                # and a failed backend write must not leave the mesh
                # serving bytes the origin never accepted
                await write_back()
                try:
                    await import_local()
                except DFError as exc:
                    log.warning("PUT import of %s failed: %s", key,
                                exc.message)
                try:
                    # dflint: disable=DF001 — unlink of a just-written temp file, µs-scale
                    os.unlink(tmp_path)
                except OSError:
                    pass
            else:
                # async mode explicitly trades durability for latency: the
                # local import serves immediately, the backend converges
                try:
                    await import_local()
                except DFError as exc:
                    log.warning("PUT import of %s failed: %s", key,
                                exc.message)

                async def write_back_bg() -> None:
                    try:
                        await write_back()
                    except Exception as exc:  # noqa: BLE001
                        _obj_reqs.labels("put", "writeback_err").inc()
                        log.error("async write-back of %s/%s FAILED — the "
                                  "object exists only in the volatile "
                                  "cache: %s", bucket, key, exc)
                    finally:
                        try:
                            # dflint: disable=DF001 — unlink of a just-written temp file, µs-scale
                            os.unlink(tmp_path)
                        except OSError:
                            pass

                task = asyncio.get_running_loop().create_task(write_back_bg())
                self._writebacks.add(task)
                task.add_done_callback(self._writebacks.discard)
        except DFError as exc:
            _obj_reqs.labels("put", "err").inc()
            try:
                # dflint: disable=DF001 — unlink of a just-written temp file, µs-scale
                os.unlink(tmp_path)
            except OSError:
                pass
            return web.json_response({"error": exc.message}, status=502)
        except BaseException:
            try:
                # dflint: disable=DF001 — unlink of a just-written temp file, µs-scale
                os.unlink(tmp_path)
            except OSError:
                pass
            raise
        _obj_reqs.labels("put", "ok").inc()
        return web.Response(status=201 if mode == "write_back" else 202)

    async def _delete_object(self, request: web.Request) -> web.Response:
        bucket = request.match_info["bucket"]
        key = request.match_info["key"]
        try:
            url = self._object_url(bucket, key)
        except DFError as exc:
            return web.json_response({"error": exc.message}, status=404)
        # delete from the WRITE BACKEND first — dropping only the cache
        # would let the next read-through GET resurrect the object from
        # the origin and report the delete a success anyway
        backend = self._backends.get(bucket)
        if backend is not None:
            try:
                await backend.delete_object(
                    getattr(backend, "bucket", "") or bucket, key)
            except DFError as exc:
                _obj_reqs.labels("delete", "err").inc()
                return web.json_response({"error": exc.message}, status=502)
        elif url.startswith("file://"):
            try:
                await asyncio.to_thread(os.unlink, url[len("file://"):])
            except FileNotFoundError:
                pass
        # drop the cached task too
        task_id = self.daemon.ptm._task_id(url, UrlMeta(tag="objstore"))
        await self.daemon.ptm.delete_task(task_id)
        _obj_reqs.labels("delete", "ok").inc()
        return web.Response(status=204)
