"""Peer-exchange (PEX) gossip plane: scheduler-less piece discovery.

Role parity: none in the reference — Dragonfly2 has exactly one
piece-discovery path, the scheduler. When the hash-ring failover
(scheduler_session.py) is exhausted, every task there falls to
back-to-source and the origin absorbs the whole pod's load even though
neighbors one ICI hop away already hold the bytes. This module removes
that single point of coordination with a BitTorrent-PEX-style exchange
of availability digests:

* every daemon periodically POSTs a compact digest — {task_id -> piece
  set, host address triple, ICI coordinates} for the tasks in its
  StorageManager — to a small fanout of known peers (ICI neighbors
  first), over the existing upload HTTP port (``POST /pex/digest``);
* the reply carries the target's digest back (push-pull anti-entropy:
  one jittered round trip per edge per interval);
* received digests land in a TTL'd local SwarmIndex (swarm_index.py);
* membership is seeded from ``pex.bootstrap`` config plus every parent
  the scheduler ever assigns (piece_engine peer_observer) and grows
  transitively through the digests themselves, which carry a peer
  sample;
* the degradation ladder (docs/RESILIENCE.md) gains a ``pex`` rung
  between ``ring_failover`` and ``back_source``: a conductor whose every
  scheduler is unreachable asks ``try_pull`` for SwarmIndex parents and
  rides the normal P2P engine against them — journaled via the flight
  recorder so dfdiag and the cluster view name the rung;
* the ticker also lazily TCP-probes stickily-demoted schedulers
  (SchedulerConnector.probe_demoted) so a healed control plane is
  noticed without waiting for the next register to trip over it.

Digest integrity: the envelope is ``sha256hex\\n<canonical JSON>``; a
body whose hash does not match is rejected and counted
(``df_pex_rejected_total``) — a corrupted digest must never plant
phantom holders. The ``pex.gossip`` faultgate site can drop, delay, or
corrupt outbound digests deterministically (chaos suite,
tests/test_pex.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import random
import time
from typing import Any, Callable

from ..common import faultgate
from ..common.errors import Code
from ..common.metrics import REGISTRY
from ..idl.messages import (PeerAddr, PeerPacket, RegisterResult, SizeScope,
                            TopologyInfo)
from ..tpu.topology import ici_hops, link_type, pod_id
from . import flight_recorder as fr
from .swarm_index import SwarmEntry, SwarmIndex

log = logging.getLogger("df.flow.pex")

DIGEST_VERSION = 1
# origins whose partial summary claims are retained for /debug/pex,
# and how long a claim outlives the last summary that refreshed it (a
# dead pod seed's stale progress must age out like every other PEX
# structure, and stale corpses must not crowd live seeds out of the cap)
MAX_FED_PARTIALS = 32
FED_PARTIALS_TTL_S = 120.0
# peers dropped from membership after this many consecutive failed rounds
PEER_FAIL_LIMIT = 3
# membership sample size carried per digest (transitive discovery)
PEER_SAMPLE = 16

_digests_sent = REGISTRY.counter(
    "df_pex_digests_sent_total",
    "PEX availability digests pushed to peers", ("result",))
_digests_received = REGISTRY.counter(
    "df_pex_digests_received_total",
    "PEX digests ingested, by transport direction", ("transport",))
_rejected = REGISTRY.counter(
    "df_pex_rejected_total",
    "PEX digests rejected before ingest", ("reason",))
_parent_hits = REGISTRY.counter(
    "df_pex_parent_hits_total",
    "pieces served by parents discovered via PEX gossip")
_primes = REGISTRY.counter(
    "df_pex_prime_total",
    "advisory parent packets pre-populated from the swarm index")
_peers_gauge = REGISTRY.gauge(
    "df_pex_peers", "peers currently in the PEX membership view")
_sched_revived = REGISTRY.counter(
    "df_pex_sched_revived_total",
    "demoted schedulers revived by the PEX ticker's lazy probe")
_fed_summaries = REGISTRY.counter(
    "df_federation_summaries_total",
    "compact inter-pod completeness summaries exchanged between elected "
    "pod seeds (task -> done/have counts, never piece sets), by "
    "direction", ("transport",))


class PeerInfo:
    """One known gossip peer (keyed by upload address)."""

    __slots__ = ("host_id", "ip", "rpc_port", "download_port", "is_seed",
                 "topology", "last_seen", "fails")

    def __init__(self, *, host_id: str, ip: str, rpc_port: int = 0,
                 download_port: int = 0, is_seed: bool = False,
                 topology: TopologyInfo | None = None):
        self.host_id = host_id
        self.ip = ip
        self.rpc_port = rpc_port
        self.download_port = download_port
        self.is_seed = is_seed
        self.topology = topology
        self.last_seen = time.monotonic()
        self.fails = 0

    @property
    def addr(self) -> str:
        return f"{self.ip}:{self.download_port}"

    def describe(self) -> dict:
        return {"host_id": self.host_id, "addr": self.addr,
                "rpc_port": self.rpc_port, "is_seed": self.is_seed,
                "fails": self.fails,
                "age_s": round(time.monotonic() - self.last_seen, 1)}


def _topo_to_wire(t: TopologyInfo | None) -> dict | None:
    if t is None:
        return None
    return {"slice": t.slice_name, "ici": list(t.ici_coords or []) or None,
            "zone": t.zone, "pod": t.pod}


def _topo_from_wire(d: dict | None) -> TopologyInfo | None:
    if not d:
        return None
    ici = d.get("ici")
    return TopologyInfo(slice_name=d.get("slice", ""),
                        ici_coords=tuple(ici) if ici else None,
                        zone=d.get("zone", ""),
                        pod=str(d.get("pod") or ""))


def seal(body: dict) -> bytes:
    """Envelope a digest body: ``sha256hex\\n<canonical JSON>``."""
    payload = json.dumps(body, sort_keys=True,
                         separators=(",", ":")).encode()
    # dflint: disable=DF001 — gossip digests are KB-scale (size-capped task/peer sample); an executor hop per round costs more than the hash
    return hashlib.sha256(payload).hexdigest().encode() + b"\n" + payload


def unseal(raw: bytes) -> dict | None:
    """Verify + parse an envelope; None (and a counted rejection) when the
    checksum, JSON, or version is bad."""
    head, sep, payload = raw.partition(b"\n")
    # dflint: disable=DF001 — gossip digests are KB-scale (size-capped task/peer sample); an executor hop per round costs more than the hash
    if not sep or hashlib.sha256(payload).hexdigest().encode() != head:
        _rejected.labels("checksum").inc()
        return None
    try:
        body = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        _rejected.labels("parse").inc()
        return None
    if not isinstance(body, dict) or body.get("v") != DIGEST_VERSION:
        _rejected.labels("version").inc()
        return None
    return body


class PexGossiper:
    """The daemon's PEX plane: membership + ticker + digest codec +
    the conductor-facing ``prime``/``try_pull`` ladder hooks."""

    def __init__(self, *, storage_mgr: Any, host_info: Callable[[], Any],
                 index: SwarmIndex | None = None, interval_s: float = 5.0,
                 fanout: int = 3, max_digest_tasks: int = 256,
                 bootstrap: list[str] | None = None,
                 tls: tuple[str, str, str] | None = None,
                 scheduler: Any = None,
                 engine_factory: Callable[[], Any] | None = None,
                 relay: Any = None,
                 verdicts: Any = None,
                 pod_scope: bool = True,
                 pod_seed: bool = False,
                 federation_peers: list[str] | None = None,
                 rng: random.Random | None = None):
        self.storage_mgr = storage_mgr
        # cross-pod federation (ROADMAP item 2): full piece-set digests
        # stay POD-SCOPED (gossip bandwidth must not grow with total
        # fleet size) — when this host has a pod identity, full digests
        # only target same-pod (or pod-less) peers. A daemon configured
        # as a pod seed additionally exchanges the COMPACT inter-pod
        # summary (build_summary: task -> completeness, never piece
        # sets) with the other pods' seeds named in federation_peers.
        self.pod_scope = pod_scope
        self.pod_seed = pod_seed
        self.federation_peers = list(federation_peers or [])
        # receiver-side view of other pods' PARTIAL progress claims from
        # inter-pod summaries (task -> have/total per origin host): never
        # indexed as coverage (a count is not a piece set), but surfaced
        # on /debug/pex so "how far along is pod B's seed" is answerable
        # without asking pod B; bounded per MAX_FED_PARTIALS
        self.fed_partials: dict[str, dict] = {}
        # per-federation-peer failure cooldown: federation_peers is
        # STATIC config, so a decommissioned seed would otherwise add a
        # full HTTP timeout to every round forever — a failed addr sits
        # out like an evicted gossip peer does (_dead_until semantics)
        self._fed_backoff: dict[str, float] = {}
        self.relay = relay               # RelayHub: watermark in digests
        # per-parent verdict ledger (daemon/verdicts.py): shunned holders
        # are dropped from the swarm index and the pex rung's candidates;
        # digests carry our LOCAL corrupt suspects as hints (receivers
        # deprioritize only — the anti-slander rule) and, when this
        # daemon self-quarantines, advertise NO tasks at all
        self.verdicts = verdicts
        self.host_info = host_info       # lazy: ports resolve after bind
        self.index = index if index is not None else SwarmIndex()
        self.interval_s = interval_s
        self.fanout = max(1, fanout)
        self.max_digest_tasks = max_digest_tasks
        self.tls = tls
        self.scheduler = scheduler       # SchedulerConnector (probe revival)
        self.engine_factory = engine_factory
        self.rng = rng or random.Random()
        self.peers: dict[str, PeerInfo] = {}    # addr -> PeerInfo
        self._dead_until: dict[str, float] = {}  # evicted addr -> cooldown
        self._self_keys_memo: tuple[str, str] | None = None
        self._bootstrap = list(bootstrap or [])
        self._task: asyncio.Task | None = None
        self._session = None             # lazy aiohttp.ClientSession
        self.rounds = 0

    # -- membership ----------------------------------------------------

    def _self_keys(self) -> tuple[str, str]:
        # cached once the upload port is bound: host_info() rebuilds the
        # full Host message (os.uname x2) and this runs per observed peer
        cached = self._self_keys_memo
        if cached is not None:
            return cached
        host = self.host_info()
        keys = (host.id, f"{host.ip}:{host.download_port}")
        if host.download_port:
            self._self_keys_memo = keys
        return keys

    def observe_peer(self, *, host_id: str, ip: str, rpc_port: int = 0,
                     download_port: int = 0, is_seed: bool = False,
                     topology: TopologyInfo | None = None,
                     direct: bool = False) -> None:
        """``direct``: first-hand liveness evidence (a digest FROM the peer
        itself, or a parent the scheduler just assigned). Indirect mentions
        — bootstrap re-seeds and other peers' gossip samples — may CREATE
        an entry but never refresh fails/last_seen: otherwise a dead peer
        that lives on in everyone's peer sample is re-blessed faster than
        PEER_FAIL_LIMIT can evict it, membership fills with immortal
        ghosts, and each ghost burns a fanout slot + an HTTP timeout per
        round. Evicted addresses sit out a cooldown before an indirect
        mention may re-create them (direct evidence re-admits at once)."""
        if not ip or not download_port:
            return
        self_id, self_addr = self._self_keys()
        addr = f"{ip}:{download_port}"
        if addr == self_addr or (host_id and host_id == self_id):
            return
        info = self.peers.get(addr)
        if info is None:
            if not direct and self._dead_until.get(addr, 0.0) \
                    > time.monotonic():
                return
            info = self.peers[addr] = PeerInfo(
                host_id=host_id or addr, ip=ip, rpc_port=rpc_port,
                download_port=download_port, is_seed=is_seed,
                topology=topology)
            self._dead_until.pop(addr, None)
        else:
            if direct:
                info.last_seen = time.monotonic()
                info.fails = 0
            if host_id:
                # bootstrap entries start keyed-by-address; the first
                # digest from the peer upgrades them to its real identity
                info.host_id = host_id
            if rpc_port:
                info.rpc_port = rpc_port
            if topology is not None:
                info.topology = topology
            info.is_seed = info.is_seed or is_seed
        _peers_gauge.set(len(self.peers))

    def observe_parent(self, parent: PeerAddr) -> None:
        """piece_engine hook: every scheduler-assigned parent joins the
        gossip membership — the mesh the scheduler built keeps working as
        the discovery substrate after the scheduler goes away. A live
        assignment is first-hand evidence (the scheduler is actively
        steering traffic at it) — but parents WE minted from the swarm
        index (prime/try_pull packets, peer_id "pex-...") are this plane's
        own hearsay and must not loop back as first-hand liveness, or a
        dead host's 60s-TTL index entries would keep re-blessing its
        membership entry past the fail-limit eviction."""
        if parent.peer_id.startswith("pex-"):
            return
        self.observe_peer(host_id="", ip=parent.ip,
                          rpc_port=parent.rpc_port,
                          download_port=parent.download_port,
                          is_seed=parent.is_seed, direct=True)

    def _targets(self) -> list[PeerInfo]:
        """Gossip fanout for this round: ICI neighbors first (cheapest
        links carry the chattiest traffic), then by freshness, with one
        random pick appended so distant membership still converges.
        Pod-scoped (``pod_scope``): when this host knows its pod, FULL
        piece-set digests go only to same-pod (or pod-less) peers —
        cross-pod availability travels as the seeds' compact summaries
        instead, so per-round gossip bytes scale with the POD, not the
        fleet."""
        host = self.host_info()
        mine = getattr(host, "topology", None)
        peers = list(self.peers.values())
        my_pod = pod_id(mine)
        if self.pod_scope and my_pod:
            local = [p for p in peers
                     if pod_id(p.topology) in ("", my_pod)]
            # lone-daemon fallback: a fresh pod's first daemon often
            # knows ONLY another pod's seed (its bootstrap) — gossiping
            # cross-pod beats being isolated entirely; the scope bounds
            # the steady state, it must never silence the boot
            peers = local or peers
        if not peers:
            return []
        peers.sort(key=lambda p: (int(link_type(mine, p.topology)),
                                  ici_hops(mine, p.topology)
                                  if mine is not None and
                                  p.topology is not None else 1 << 16,
                                  -p.last_seen, p.addr))
        picked = peers[:self.fanout]
        rest = peers[self.fanout:]
        if rest:
            picked.append(self.rng.choice(rest))
        return picked

    # -- digest codec --------------------------------------------------

    def build_digest(self) -> dict:
        host = self.host_info()
        tasks = []
        selfq = self.verdicts is not None and self.verdicts.self_quarantined
        for ts in () if selfq else self.storage_mgr.tasks():
            md = ts.md
            if not md.pieces and not (md.done and md.success):
                continue
            done = bool(md.done and md.success)
            entry = {"task_id": md.task_id,
                     "total": md.total_piece_count,
                     "content_length": md.content_length,
                     "piece_size": md.piece_size,
                     "done": done}
            if not done:
                entry["pieces"] = sorted(md.pieces)
                if self.relay is not None:
                    # the advertised landing watermark: pieces arriving
                    # on this daemon NOW — cut-through-servable, counted
                    # toward coverage only while the watermark stays
                    # fresh (SwarmEntry.progress_fresh)
                    wm = sorted({i.piece_num for i in
                                 self.relay.inflight_infos(md.task_id)}
                                - set(md.pieces))
                    if wm:
                        entry["relay"] = wm
            tasks.append(entry)
            if len(tasks) >= self.max_digest_tasks:
                break
        sample = list(self.peers.values())
        if len(sample) > PEER_SAMPLE:
            sample = self.rng.sample(sample, PEER_SAMPLE)
        digest = {
            "v": DIGEST_VERSION,
            "origin": {"host_id": host.id, "ip": host.ip,
                       "rpc_port": host.port,
                       "download_port": host.download_port,
                       "is_seed": int(host.type) != 0,
                       "selfq": selfq,
                       "topology": _topo_to_wire(
                           getattr(host, "topology", None))},
            "peers": [{"host_id": p.host_id, "ip": p.ip,
                       "rpc_port": p.rpc_port,
                       "download_port": p.download_port,
                       "is_seed": p.is_seed,
                       "topology": _topo_to_wire(p.topology)}
                      for p in sample],
            "tasks": tasks,
        }
        if self.verdicts is not None:
            # LOCAL corrupt-shun verdicts only, bounded: receivers treat
            # these as hearsay hints (deprioritize, never shun) — see the
            # anti-slander contract in daemon/verdicts.py
            suspects = self.verdicts.shunned_addrs()[:8]
            if suspects:
                digest["suspects"] = suspects
        return digest

    def envelope(self) -> bytes:
        return seal(self.build_digest())

    def build_summary(self) -> dict:
        """The compact inter-pod digest: per task one COMPLETENESS row —
        done flag, landed count, geometry — and no piece sets, no peer
        sample. This is what elected pod seeds exchange across the DCN:
        a complete cross-pod holder is indexable (a seed can pull whole
        tasks through it), a partial one is a counter for observability
        only (``ingest`` skips pieceless partial rows, so a summary can
        never plant phantom partial coverage the pex rung would park
        on). Size is O(tasks), independent of pod or fleet size."""
        host = self.host_info()
        tasks = []
        selfq = self.verdicts is not None and self.verdicts.self_quarantined
        for ts in () if selfq else self.storage_mgr.tasks():
            md = ts.md
            if not md.pieces and not (md.done and md.success):
                continue
            tasks.append({"task_id": md.task_id,
                          "total": md.total_piece_count,
                          "content_length": md.content_length,
                          "piece_size": md.piece_size,
                          "done": bool(md.done and md.success),
                          "have": len(md.pieces)})
            if len(tasks) >= self.max_digest_tasks:
                break
        return {
            "v": DIGEST_VERSION,
            "kind": "summary",
            "origin": {"host_id": host.id, "ip": host.ip,
                       "rpc_port": host.port,
                       "download_port": host.download_port,
                       "is_seed": int(host.type) != 0,
                       "selfq": selfq,
                       "topology": _topo_to_wire(
                           getattr(host, "topology", None))},
            "peers": [],
            "tasks": tasks,
        }

    def summary_envelope(self) -> bytes:
        return seal(self.build_summary())

    def ingest(self, raw: bytes, *, transport: str = "push") -> bool:
        """Verify + merge a received envelope. False = rejected (checksum,
        JSON, version, or field types — the seal only proves the sender
        sealed these bytes, not that the fields are well-typed, so the
        whole body is coerced BEFORE anything mutates membership: a
        version-skewed peer must produce a counted rejection, not a 500
        and a half-merged view)."""
        body = unseal(raw)
        if body is None:
            return False
        try:
            body_kind = str(body.get("kind") or "digest")
            partials: dict[str, dict] = {}
            origin = body.get("origin") or {}
            topo = _topo_from_wire(origin.get("topology"))
            host_id = str(origin.get("host_id") or "")
            ip = str(origin.get("ip") or "")
            rpc_port = int(origin.get("rpc_port") or 0)
            download_port = int(origin.get("download_port") or 0)
            is_seed = bool(origin.get("is_seed"))
            origin_selfq = bool(origin.get("selfq"))
            suspects = [str(a) for a in body.get("suspects") or []][:16]
            sampled = [dict(host_id=str(p.get("host_id") or ""),
                            ip=str(p.get("ip") or ""),
                            rpc_port=int(p.get("rpc_port") or 0),
                            download_port=int(p.get("download_port") or 0),
                            is_seed=bool(p.get("is_seed")),
                            topology=_topo_from_wire(p.get("topology")))
                       for p in body.get("peers") or []]
            entries = []
            for t in body.get("tasks") or []:
                task_id = str(t.get("task_id") or "")
                if not task_id:
                    continue
                done = bool(t.get("done"))
                pieces = (None if done
                          else {int(n) for n in t.get("pieces") or []})
                relay_pieces = (None if done
                                else {int(n) for n in t.get("relay") or []}
                                or None)
                if not done and not pieces and not relay_pieces:
                    if body_kind == "summary":
                        # partial cross-pod claims are NEVER coverage (a
                        # count is not a piece set) but they ARE progress
                        # observability — retained for /debug/pex
                        partials[task_id] = {
                            "have": int(t.get("have") or 0),
                            "total": int(t.get("total", -1))}
                    continue
                entries.append((task_id, SwarmEntry(
                    host_id=host_id or f"{ip}:{download_port}", ip=ip,
                    rpc_port=rpc_port, download_port=download_port,
                    is_seed=is_seed, topology=topo, pieces=pieces,
                    relay_pieces=relay_pieces,
                    total_pieces=int(t.get("total", -1)),
                    content_length=int(t.get("content_length", -1)),
                    piece_size=int(t.get("piece_size", 0)), done=done)))
        except (ValueError, TypeError, AttributeError):
            _rejected.labels("parse").inc()
            return False
        self_id, self_addr = self._self_keys()
        if host_id == self_id or f"{ip}:{download_port}" == self_addr:
            return True      # our own digest reflected back: nothing to do
        # the digest came FROM its origin: first-hand liveness; the peer
        # sample is hearsay and may only create entries, never refresh
        self.observe_peer(host_id=host_id, ip=ip, rpc_port=rpc_port,
                          download_port=download_port, is_seed=is_seed,
                          topology=topo, direct=True)
        for p in sampled:
            self.observe_peer(**p)
        origin_addr = f"{ip}:{download_port}"
        if self.verdicts is not None:
            # third-party accusations are hearsay: HINT only (the
            # accused host is deprioritized in parent ordering, never
            # shunned — one forged digest must not evict an honest host)
            for a in suspects:
                if a != self_addr and a != origin_addr:
                    self.verdicts.hint(a)
        locally_shunned = (self.verdicts is not None
                           and self.verdicts.shunned(origin_addr))
        if origin_selfq or locally_shunned:
            # a self-quarantined origin asked to be excluded; a locally-
            # shunned one served US corruption first-hand — either way its
            # availability claims stop being indexed (and prior claims go)
            self.index.forget_host(host_id or origin_addr)
        elif ip and download_port:
            for task_id, entry in entries:
                self.index.update(task_id, entry)
        if body_kind == "summary" and not origin_selfq:
            key = host_id or origin_addr
            self.fed_partials.pop(key, None)
            self._purge_fed_partials()
            if partials and len(self.fed_partials) < MAX_FED_PARTIALS:
                self.fed_partials[key] = {"at": time.monotonic(),
                                          "tasks": partials}
        _digests_received.labels(transport).inc()
        return True

    def _purge_fed_partials(self, *, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        for key in [k for k, v in self.fed_partials.items()
                    if now - v["at"] > FED_PARTIALS_TTL_S]:
            del self.fed_partials[key]

    # -- gossip rounds -------------------------------------------------

    def _get_session(self):
        import aiohttp
        if self._session is None or self._session.closed:
            ssl_ctx = None
            if self.tls is not None:
                import ssl as _ssl
                cert, key, ca = self.tls
                ssl_ctx = _ssl.create_default_context(cafile=ca)
                ssl_ctx.load_cert_chain(cert, key)
                ssl_ctx.check_hostname = False   # fleet CA authenticates
                ssl_ctx.verify_mode = _ssl.CERT_REQUIRED
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=16, ssl=ssl_ctx),
                timeout=aiohttp.ClientTimeout(total=5.0))
        return self._session

    @property
    def _scheme(self) -> str:
        return "https" if self.tls is not None else "http"

    async def round(self) -> int:
        """One gossip round: purge, push-pull with the fanout targets,
        probe demoted schedulers. Returns digests successfully exchanged.
        Public so tests and operators can drive it deterministically."""
        self.rounds += 1
        self.index.purge()
        self._purge_fed_partials()
        if self.verdicts is not None:
            # verdicts may have flipped since the entries landed: a
            # holder shunned mid-interval stops being offerable NOW, not
            # at its next digest
            for p in list(self.peers.values()):
                if self.verdicts.shunned(p.addr):
                    self.index.forget_host(p.host_id)
        for addr in self._bootstrap:
            ip, _, port = addr.rpartition(":")
            if ip and port.isdigit():
                self.observe_peer(host_id="", ip=ip,
                                  download_port=int(port))
        exchanged = 0
        for peer in self._targets():
            try:
                if faultgate.ARMED:
                    # fail/delay/hang drop or stall THIS edge's exchange —
                    # the round moves on to the next target (fail) or rides
                    # its own HTTP timeout (hang), exactly like a wedged
                    # peer; 'corrupt' flips an envelope byte so the
                    # receiver's checksum rejects it
                    await faultgate.fire("pex.gossip", key=peer.addr)
                payload = self.envelope()
                if faultgate.ARMED:
                    payload = faultgate.corrupt("pex.gossip", payload,
                                                key=peer.addr)
                url = f"{self._scheme}://{peer.addr}/pex/digest"
                async with self._get_session().post(url,
                                                    data=payload) as resp:
                    if resp.status != 200:
                        raise OSError(f"HTTP {resp.status}")
                    # anti-entropy pull: the reply is the peer's digest
                    self.ingest(await resp.read(), transport="pull")
                peer.last_seen = time.monotonic()
                peer.fails = 0
                exchanged += 1
                _digests_sent.labels("ok").inc()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - peer churn is normal
                _digests_sent.labels("error").inc()
                peer.fails += 1
                log.debug("pex exchange with %s failed (%d/%d): %s",
                          peer.addr, peer.fails, PEER_FAIL_LIMIT, exc)
                if peer.fails >= PEER_FAIL_LIMIT:
                    self.peers.pop(peer.addr, None)
                    self.index.forget_host(peer.host_id)
                    # cooldown before hearsay (bootstrap re-seeds, other
                    # peers' samples) may re-create the entry — a dead
                    # address must not ride re-creation back to fails=0
                    # every round; a digest FROM the address re-admits it
                    # immediately
                    self._dead_until[peer.addr] = (
                        time.monotonic() + 10 * self.interval_s)
                    _peers_gauge.set(len(self.peers))
        exchanged += await self._federation_round()
        await self._probe_demoted_schedulers()
        return exchanged

    async def _federation_round(self) -> int:
        """The inter-pod half: an elected pod seed push-pulls the COMPACT
        completeness summary with the other pods' seeds
        (``federation_peers``). Rides the same ``pex.gossip`` faultgate
        site as in-pod digests, with its own failure cooldown (the peer
        list is static config, so a dead seed backs off instead of being
        evicted), and never grows with pod size — cross-pod gossip is
        O(seeds x tasks), which is how the PEX plane scales to a fleet
        without every daemon gossiping with every other pod."""
        if not self.pod_seed or not self.federation_peers:
            return 0
        exchanged = 0
        now = time.monotonic()
        window = [a for a in self.federation_peers
                  if self._fed_backoff.get(a, 0.0) <= now]
        if len(window) > self.fanout + 1:
            # rotate the window by round so every configured seed pair
            # eventually exchanges — a fixed prefix would leave pods
            # beyond it permanently blind to each other (summaries carry
            # no transitive re-gossip by design)
            start = self.rounds % len(window)
            window = [window[(start + k) % len(window)]
                      for k in range(self.fanout + 1)]
        for addr in window:
            ip, _, port = addr.rpartition(":")
            if not ip or not port.isdigit():
                continue
            try:
                if faultgate.ARMED:
                    await faultgate.fire("pex.gossip", key=addr)
                payload = self.summary_envelope()
                if faultgate.ARMED:
                    payload = faultgate.corrupt("pex.gossip", payload,
                                                key=addr)
                url = f"{self._scheme}://{addr}/pex/summary"
                async with self._get_session().post(url,
                                                    data=payload) as resp:
                    if resp.status != 200:
                        raise OSError(f"HTTP {resp.status}")
                    self.ingest(await resp.read(), transport="summary")
                exchanged += 1
                self._fed_backoff.pop(addr, None)
                _fed_summaries.labels("sent").inc()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - seed churn is normal
                _fed_summaries.labels("error").inc()
                self._fed_backoff[addr] = (time.monotonic()
                                           + 10 * self.interval_s)
                log.debug("inter-pod summary with %s failed: %s", addr, exc)
        return exchanged

    async def _probe_demoted_schedulers(self) -> None:
        """Lazy revival ride-along: without this, a demoted scheduler is
        only ever re-probed when some task's register happens to hash near
        it — a quiet daemon would sit on the pex/back_source rungs long
        after the control plane healed."""
        sched = self.scheduler
        probe = getattr(sched, "probe_demoted", None)
        if probe is None or not getattr(sched, "demoted", lambda: ())():
            return
        try:
            revived = await probe()
            if revived:
                _sched_revived.inc(len(revived))
                log.info("pex ticker revived schedulers: %s", revived)
        except Exception as exc:  # noqa: BLE001 - probe is best-effort
            log.debug("scheduler probe failed: %s", exc)

    async def _loop(self, *, initial_round: bool = False) -> None:
        if initial_round:
            # warm-restart re-seed: push the reloaded-from-disk digest to
            # the bootstrap/known peers immediately so the swarm re-learns
            # this holder within one round, not one jittered interval —
            # the PR 4/5 seed-restart scenario's cold window closed
            try:
                await self.round()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - keep the ticker alive
                log.exception("pex initial round failed")
        while True:
            # jittered so a pod's daemons never gossip in phase
            await asyncio.sleep(self.interval_s *
                                self.rng.uniform(0.6, 1.4))
            try:
                await self.round()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - keep the ticker alive
                log.exception("pex round failed")

    async def start(self, *, initial_round: bool = False) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._loop(initial_round=initial_round))

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._session is not None and not self._session.closed:
            await self._session.close()
            self._session = None

    # -- degradation-ladder hooks (conductor) --------------------------

    def _candidates(self, conductor) -> list:
        host = self.host_info()
        mine = getattr(host, "topology", None)
        entries = self.index.parents_for(
            conductor.task_id,
            self_topology=mine,
            exclude_host=host.id)
        if self.verdicts is not None:
            # the pex rung has no scheduler to rescue it from a poisoner:
            # locally-shunned holders are OUT — and they are dropped
            # BEFORE the pod-first gate below, or a shunned in-pod
            # holder would both satisfy coverage and discard the clean
            # cross-pod fallback, pushing the pull all the way to origin
            entries = [e for e in entries
                       if not self.verdicts.shunned(e.addr)]
        my_pod = pod_id(mine)
        if my_pod and entries:
            # pod-first rung: when pod-local holders (incl. pod-less
            # plain peers) cover everything this conductor still needs,
            # never cross the DCN — cross-pod entries (the seeds'
            # summary-advertised holders) are the fallback for content
            # the pod does not hold, not a parallel source that would
            # turn every cache miss into N DCN streams
            local = [e for e in entries
                     if pod_id(e.topology) in ("", my_pod)]
            if local and self._covers_task(local, conductor):
                entries = local
        if self.verdicts is not None:
            # hinted/suspect holders sort last (deprioritized, still
            # usable — the anti-slander rule's ceiling for hearsay)
            entries.sort(key=lambda e: 1 if self.verdicts.deprioritized(
                e.addr) else 0)
        return entries

    def _packet(self, conductor, entries, *, advisory: bool) -> PeerPacket:
        mine = getattr(self.host_info(), "topology", None)
        return PeerPacket(
            task_id=conductor.task_id, src_peer_id=conductor.peer_id,
            advisory=advisory,
            candidate_peers=[
                PeerAddr(peer_id=f"pex-{e.host_id}", ip=e.ip,
                         rpc_port=e.rpc_port,
                         download_port=e.download_port,
                         link=link_type(mine, e.topology),
                         is_seed=e.is_seed)
                for e in entries if e.rpc_port and e.download_port])

    def prime(self, conductor, session) -> None:
        """Hot-task pre-population: enqueue swarm-known holders as an
        ADVISORY packet on a live scheduler session, so the engine has
        parents to pull from before (or while) the scheduler's own
        assignment lands. Advisory packets never prune the scheduler's
        assignment (piece_engine honors the flag) — the scheduler stays
        the authority whenever it is reachable."""
        entries = self._candidates(conductor)
        if not entries:
            return
        packet = self._packet(conductor, entries[:self.fanout + 1],
                              advisory=True)
        if not packet.candidate_peers:
            return
        session.packets.put_nowait(packet)
        _primes.inc()

    def _covers_task(self, entries, conductor) -> bool:
        """Coverage gate for the pex rung: there is no scheduler behind a
        pex pull, so nobody rescues it if the gossip-known holders turn
        out not to have the whole task — the engine would land the covered
        pieces and then park forever waiting for announcements that can
        never come (a seed riding this rung while its leechers wait on IT
        is a distributed deadlock: the chaos seed-restart scenario).
        Proceed only when some holder is complete, or the partial holders'
        piece sets collectively cover every piece this conductor still
        needs; otherwise decline and let the ladder continue to
        back_source.

        In-flight watermark claims (``relay_pieces``) count toward
        coverage ONLY while the holder's watermark is fresh
        (``progress_fresh`` within the index's progress TTL): a stale
        watermark is a download that died mid-flight — counting its
        abandoned pieces would re-open the exact parked-forever hole this
        gate closed (the PR 5 seed-restart fix)."""
        if any(e.done or e.pieces is None for e in entries):
            return True
        total = max((e.total_pieces for e in entries), default=-1)
        if total < 0:
            # nobody is complete and nobody knows the geometry: the pull
            # could not even tell how much is missing
            return False
        now = time.monotonic()
        ttl = self.index.progress_ttl_s
        union: set[int] = set()
        for e in entries:
            union |= e.pieces or set()
            if e.relay_pieces and e.progress_fresh(now, ttl):
                union |= e.relay_pieces
        need = set(range(total)) - set(conductor.ready)
        return need <= union

    async def try_pull(self, conductor) -> bool:
        """The ``pex`` rung: serve the task from SwarmIndex holders with a
        fresh P2P engine and a synthetic session — no scheduler anywhere
        in the loop. False = rung declined (no holders / no engine) and
        the ladder continues to back_source."""
        if self.engine_factory is None:
            return False
        entries = self._candidates(conductor)
        if not entries:
            return False
        if not self._covers_task(entries, conductor):
            return False
        geo = next((e for e in entries if e.content_length >= 0), None)
        packet = self._packet(conductor, entries, advisory=False)
        if not packet.candidate_peers:
            return False
        if conductor.flight is not None:
            conductor.flight.rung(fr.RUNG_PEX)
        conductor.log.info("pex rung: pulling from %d gossip-discovered "
                           "holder(s)", len(packet.candidate_peers))
        session = _PexSession(RegisterResult(
            task_id=conductor.task_id, size_scope=SizeScope.NORMAL,
            content_length=geo.content_length if geo is not None else -1,
            piece_size=geo.piece_size if geo is not None else 0), [packet])
        engine = self.engine_factory()
        return await engine.pull(conductor, session)

    # -- debug surface -------------------------------------------------

    def _fed_partials_view(self) -> dict:
        self._purge_fed_partials()
        now = time.monotonic()
        return {key: {"age_s": round(now - v["at"], 1), "tasks": v["tasks"]}
                for key, v in self.fed_partials.items()}

    def debug_snapshot(self) -> dict:
        host = self.host_info()
        topo = getattr(host, "topology", None)
        return {
            "interval_s": self.interval_s,
            "fanout": self.fanout,
            "rounds": self.rounds,
            # this daemon's own fabric position: podscope stitches the
            # two-level tree's per-tier edge marks from these
            "host": {"pod": pod_id(topo),
                     "slice": getattr(topo, "slice_name", ""),
                     "zone": getattr(topo, "zone", ""),
                     "pod_seed": self.pod_seed},
            "federation_peers": list(self.federation_peers),
            "federation_partials": self._fed_partials_view(),
            "peers": [p.describe() for p in self.peers.values()],
            "swarm": self.index.snapshot(),
        }


class _PexSession:
    """Synthetic scheduler session for the pex rung: the engine consumes
    ``result``/``packets`` exactly as from a real PeerSession; piece
    reports have no scheduler to go to, so they only feed the
    ``df_pex_parent_hits_total`` counter."""

    # no scheduler behind this session: the engine must self-abort on a
    # stall instead of waiting for a control plane that will never act
    rescuable = False

    def __init__(self, result: RegisterResult, packets: list[PeerPacket]):
        self.result = result
        self.packets: asyncio.Queue = asyncio.Queue()
        for p in packets:
            self.packets.put_nowait(p)

    async def report_piece(self, result) -> None:
        if result.success and result.dst_peer_id \
                and int(result.code or 0) == int(Code.OK):
            _parent_hits.inc()

    async def close(self, *, success: bool) -> None:
        return None


def add_pex_routes(router, gossiper: PexGossiper) -> None:
    """Upload-port routes: ``GET /pex/digest`` (pull), ``POST /pex/digest``
    (push; the 200 body is our digest — the pull half of push-pull), and
    ``GET /debug/pex`` (membership + swarm snapshot). Mesh-internal and
    ring-bounded like /debug/flight, so not gated behind the debug flag."""
    from aiohttp import web

    async def get_digest(_r: web.Request) -> web.Response:
        return web.Response(body=gossiper.envelope(),
                            content_type="application/octet-stream")

    async def post_digest(request: web.Request) -> web.Response:
        raw = await request.read()
        if not gossiper.ingest(raw, transport="push"):
            raise web.HTTPBadRequest(text="digest verification failed")
        return web.Response(body=gossiper.envelope(),
                            content_type="application/octet-stream")

    async def get_summary(_r: web.Request) -> web.Response:
        return web.Response(body=gossiper.summary_envelope(),
                            content_type="application/octet-stream")

    async def post_summary(request: web.Request) -> web.Response:
        # the inter-pod half: another pod's seed pushes its completeness
        # summary; the 200 body is OUR summary (push-pull, like digests)
        raw = await request.read()
        if not gossiper.ingest(raw, transport="summary"):
            raise web.HTTPBadRequest(text="summary verification failed")
        _fed_summaries.labels("received").inc()
        return web.Response(body=gossiper.summary_envelope(),
                            content_type="application/octet-stream")

    async def debug_pex(_r: web.Request) -> web.Response:
        return web.json_response(gossiper.debug_snapshot())

    router.add_get("/pex/digest", get_digest)
    router.add_post("/pex/digest", post_digest)
    router.add_get("/pex/summary", get_summary)
    router.add_post("/pex/summary", post_summary)
    router.add_get("/debug/pex", debug_pex)
