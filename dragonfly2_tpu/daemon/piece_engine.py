"""P2P piece engine: pulls a task's pieces from parent peers.

Role parity: reference ``client/daemon/peer/peertask_conductor.go`` P2P half —
``pullPiecesWithP2P`` (:544), ``receivePeerPacket`` (:659), the 4 piece
workers (:976-1010) — plus ``peertask_piecetask_synchronizer.go`` (one
``SyncPieceTasks`` bidi stream per parent feeding the dispatcher).

``pull`` returns:
  * True  — task completed via P2P (conductor verifies + finalizes)
  * False — fall back to origin (the back-source ladder: NeedBackSource from
    the scheduler, no parents within the schedule timeout, or all parents
    dying without replacement)
and raises DFError for hard failures.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import TYPE_CHECKING

from ..common import health
from ..common.bufpool import POOL
from ..common.errors import Code, DFError
from ..common.metrics import BYTES_BUCKETS, REGISTRY
from ..idl.messages import (PeerAddr, PeerPacket, PieceInfo, PieceResult,
                            PieceTaskRequest, SizeScope)
from ..rpc.client import ChannelPool, ServiceClient
from . import flight_recorder as fr
from .piece_dispatcher import ENDGAME_PIECES, Dispatch, PieceDispatcher
from .piece_downloader import PieceDownloader

if TYPE_CHECKING:  # pragma: no cover
    from .conductor import PeerTaskConductor
    from .scheduler_session import PeerSession

log = logging.getLogger("df.flow.engine")

DAEMON_SERVICE = "df.daemon.Daemon"

_p2p_pieces = REGISTRY.counter("df_p2p_piece_total",
                               "pieces fetched from peers", ("result",))
_p2p_piece_bytes = REGISTRY.histogram(
    "df_p2p_piece_bytes", "size of each piece landed from a peer",
    buckets=BYTES_BUCKETS)


class _Synchronizer:
    """One SyncPieceTasks stream against one parent daemon."""

    def __init__(self, engine: "PieceEngine", conductor: "PeerTaskConductor",
                 parent: PeerAddr):
        self.engine = engine
        self.conductor = conductor
        self.parent = parent
        self.task: asyncio.Task | None = None
        self.stream = None              # live SyncPieceTasks stream
        self._seen: set[int] = set()    # piece nums this parent announced

    def start(self) -> None:
        self.task = asyncio.get_running_loop().create_task(self._run())

    def exhausted(self) -> bool:
        """Parent has announced every piece of the task — pinging it cannot
        reveal anything new."""
        total = self.conductor.total_pieces
        return total >= 0 and len(self._seen) >= total

    async def ping(self) -> None:
        """Starvation signal: ask the parent for more work (super-seeding
        parents respond by revealing more pieces; others re-announce)."""
        if self.exhausted():
            return
        stream = self.stream
        if stream is None:
            return
        try:
            await stream.write(PieceTaskRequest(
                task_id=self.conductor.task_id,
                src_peer_id=self.conductor.peer_id,
                dst_peer_id=self.parent.peer_id,
                start_num=0, limit=1 << 20,
                src_slice=self.engine.slice_name))
        except Exception:  # noqa: BLE001 - stream may be closing
            pass

    async def _run(self) -> None:
        addr = f"{self.parent.ip}:{self.parent.rpc_port}"
        try:
            client = self.engine.peer_client(addr)
            stream = client.stream_stream("SyncPieceTasks")
            self.stream = stream
            await stream.write(PieceTaskRequest(
                task_id=self.conductor.task_id,
                src_peer_id=self.conductor.peer_id,
                dst_peer_id=self.parent.peer_id,
                start_num=0, limit=1 << 20,
                src_slice=self.engine.slice_name))
            try:
                while True:
                    packet = await stream.read()
                    if packet is None:
                        break
                    await self._on_packet(packet)
            finally:
                self.stream = None
                stream.cancel()
        except asyncio.CancelledError:
            raise
        except DFError as exc:
            log.debug("sync with %s ended: %s", self.parent.peer_id, exc)
            await self.engine.dispatcher.remove_parent(self.parent.peer_id)
        except Exception as exc:  # noqa: BLE001 - parent went away
            log.debug("sync with %s failed: %s", self.parent.peer_id, exc)
            await self.engine.dispatcher.remove_parent(self.parent.peer_id)

    async def _on_packet(self, packet) -> None:
        if packet.content_length >= 0 and self.conductor.piece_size == 0:
            self.conductor.set_content_info(packet.content_length,
                                            packet.piece_size)
            self.engine.apply_shard_state(self.conductor)
        if self.conductor.piece_size == 0:
            # parent itself doesn't know the geometry yet (unknown-length
            # origin mid-flight): skip — the done-refresh re-announces all
            return
        dst_addr = packet.dst_addr or f"{self.parent.ip}:{self.parent.download_port}"
        if not self.engine._admissible(self.parent.peer_id, dst_addr):
            # locally-shunned address: its announcements must not grow a
            # dispatcher slot, however it got a sync stream
            return
        await self.engine.dispatcher.add_parent(self.parent.peer_id, dst_addr,
                                                is_seed=self.parent.is_seed,
                                                link=self.parent.link)
        for p in packet.piece_infos or []:
            self._seen.add(p.piece_num)
        infos = [p for p in (packet.piece_infos or [])
                 if p.piece_num not in self.conductor.ready]
        if infos:
            # content-store consult BEFORE dispatch: announced pieces whose
            # digests are already on disk (this task's surviving pieces, or
            # any task's under the same digest) are placed locally — the
            # dispatcher never even queues a pull for them
            placed = await self.conductor.place_from_store(infos)
            if placed:
                infos = [p for p in infos if p.piece_num not in placed]
        if infos:
            await self.engine.dispatcher.announce(self.parent.peer_id, infos)

    def stop(self) -> None:
        if self.task is not None:
            self.task.cancel()


class _SpanHandle:
    """Engine-side relay-span lifecycle: called by the downloader with the
    pooled buffer once acquired (registers the in-flight span), retired by
    the engine once the span's pieces have landed — always before the
    buffer returns to the pool. A no-op when the relay plane is off."""

    __slots__ = ("relay", "task_id", "pieces", "span")

    def __init__(self, relay, task_id: str, pieces: list[PieceInfo]):
        self.relay = relay
        self.task_id = task_id
        self.pieces = pieces
        self.span = None

    def __call__(self, buf):
        if self.relay is None:
            return None
        base = self.pieces[0].range_start
        size = sum(p.range_size for p in self.pieces)
        self.span = self.relay.open_span(self.task_id, base, size, buf,
                                         self.pieces)
        return self.span

    def retire(self) -> None:
        if self.span is not None and self.relay is not None:
            self.relay.retire(self.span)
            self.span = None


class PieceEngine:
    def __init__(self, *, parallelism: int = 4,
                 schedule_timeout_s: float = 30.0,
                 piece_timeout_s: float = 60.0,
                 downloader: PieceDownloader | None = None,
                 channel_pool: ChannelPool | None = None,
                 slice_name: str = "",
                 peer_observer=None,
                 relay=None,
                 verdicts=None):
        self.parallelism = parallelism
        self.slice_name = slice_name    # advertised to super-seeding parents
        # PEX membership hook (daemon/pex.py): every parent the scheduler
        # assigns is observed so the gossip plane knows the mesh
        self.peer_observer = peer_observer
        # per-parent verdict ledger (daemon/verdicts.py): typed failure
        # verdicts recorded here; parents the ledger shuns on local
        # corrupt evidence are never admitted to the dispatcher — even
        # when the scheduler (or the PEX rung) keeps offering them
        self.verdicts = verdicts
        # cut-through relay hub (daemon/relay.py): every in-flight span
        # this engine downloads becomes readable by the upload server's
        # streaming range path while its bytes are still arriving
        self.relay = relay
        self.schedule_timeout_s = schedule_timeout_s
        self.piece_timeout_s = piece_timeout_s
        self.downloader = downloader or PieceDownloader(timeout_s=piece_timeout_s)
        self._own_downloader = downloader is None
        # channel pool may be shared daemon-wide so parent connections persist
        self._channels = channel_pool if channel_pool is not None else ChannelPool()
        self._own_channels = channel_pool is None
        self.dispatcher = PieceDispatcher()
        self._synchronizers: dict[str, _Synchronizer] = {}
        self._current_parents: dict[str, PeerAddr] = {}  # latest assignment
        self._need_back_source = False
        self._first_parent = asyncio.Event()
        self._last_ping = 0.0
        # starvation-ping pacing: per-engine jittered base so a fan-out's
        # children never ping in phase, exponential while pings produce no
        # new announcements (a struggling swarm must not spend its one core
        # on 100s of control messages/s — the r04 16-leecher convoy),
        # reset to base on progress
        self._ping_base = 0.1 * random.uniform(0.9, 1.5)
        self._ping_interval = self._ping_base
        self._announced_at_ping = -1
        self._shards_applied = False

    def peer_client(self, addr: str) -> ServiceClient:
        return ServiceClient(self._channels.get(addr), DAEMON_SERVICE)

    def _relay_opener(self, conductor, pieces: list[PieceInfo]) -> _SpanHandle:
        return _SpanHandle(self.relay, conductor.task_id, pieces)

    def apply_shard_state(self, conductor) -> None:
        """Push the conductor's sharded-task piece classes into the
        dispatcher once geometry is known: the needed subset (pieces
        outside it are never dispatched) and the swap-class set (held
        off seed parents for the bounded swap window so co-located
        replicas supply them over ICI-near P2P). Idempotent; re-applied
        on widen (a joiner requesting other shards)."""
        if conductor.shard_tracker is None or conductor.piece_size <= 0:
            return
        if self._shards_applied \
                and self.dispatcher.needed == conductor.needed_pieces \
                and self.dispatcher.swap_nums == conductor.swap_piece_nums:
            return
        self._shards_applied = True
        self.dispatcher.set_shard_state(conductor.needed_pieces,
                                        conductor.swap_piece_nums)

    # ------------------------------------------------------------------

    async def pull(self, conductor: "PeerTaskConductor",
                   session: "PeerSession") -> bool:
        self.dispatcher.ordered = conductor.ordered
        result = session.result
        try:
            if result.size_scope == SizeScope.EMPTY:
                conductor.set_content_info(0)
                return True
            if result.size_scope == SizeScope.TINY and result.direct_content:
                data = result.direct_content
                conductor.set_content_info(len(data))
                await conductor.on_piece_from_peer(0, 0, data, 0, "scheduler")
                return True
            if (result.size_scope == SizeScope.SMALL
                    and result.single_piece is not None
                    and result.single_piece.piece_info is not None):
                ok = await self._pull_single(conductor, session,
                                             result.single_piece)
                if ok:
                    return True
                # fall through to the normal path: scheduler may still help
            return await self._pull_normal(conductor, session)
        finally:
            await self._teardown()

    async def _pull_single(self, conductor, session, single) -> bool:
        info: PieceInfo = single.piece_info
        if session.result.content_length >= 0:
            conductor.set_content_info(session.result.content_length,
                                       session.result.piece_size)
        else:
            conductor.set_content_info(info.range_size)
        t0 = int(time.time() * 1000)
        flight = conductor.flight
        on_first = None
        if flight is not None:
            flight.event(fr.DISPATCHED, info.piece_num, single.dst_peer_id)

            def on_first(_num=info.piece_num, _pid=single.dst_peer_id):
                flight.event(fr.FIRST_BYTE, _num, _pid)
        span = self._relay_opener(conductor, [info])
        try:
            with health.PLANE.watchdog.section(
                    "piece.wire", health.PLANE.slo.section_deadline_s(),
                    stage="wire"):
                wire_meta: dict = {}
                data, cost = await self.downloader.download_piece(
                    dst_addr=single.dst_addr, task_id=conductor.task_id,
                    src_peer_id=conductor.peer_id, piece=info,
                    on_first_byte=on_first, relay_open=span,
                    qos_class=getattr(conductor, "qos_class", ""),
                    meta=wire_meta)
        except DFError as exc:
            _p2p_pieces.labels("fail").inc()
            # backpressure is not a failure VERDICT (parity with the
            # span path's requeue-without-strike): a busy 503 earns no
            # typed code, no flight failure event, no ledger entry
            busy = exc.code == Code.CLIENT_PEER_BUSY
            fcode = "" if busy else self._fail_code(exc)
            if not busy:
                self._note_fail(conductor, info, single.dst_peer_id,
                                single.dst_addr, fcode)
            await session.report_piece(self._piece_result(
                conductor, info, single.dst_peer_id, t0, ok=False,
                code=exc.code, fail_code=fcode))
            return False
        t_wire = flight.now_ms() if flight is not None else 0.0
        try:
            placed, corrupt, raced = await conductor.on_span_from_peer(
                single.dst_peer_id, [info], data, cost)
        finally:
            # retire BEFORE the pool release: a relay reader must never
            # copy from a recycled buffer (landed bytes serve from disk)
            span.retire()
            POOL.release(data)
        if corrupt:
            self._note_corrupt(conductor, info, single.dst_peer_id,
                               addr=single.dst_addr,
                               relayed=wire_meta.get("relayed", False))
            await session.report_piece(self._piece_result(
                conductor, info, single.dst_peer_id, t0, ok=False,
                code=Code.CLIENT_DIGEST_MISMATCH, fail_code="corrupt",
                relayed=wire_meta.get("relayed", False)))
            return False
        if raced:
            # an endgame racer is mid-landing: its outcome is unknown, so
            # report NOTHING for this piece — the racer's own path settles
            # it (reporting ok here would orphan the piece if the racer's
            # copy fails verification)
            return True
        if flight is not None and placed:
            flight.event(fr.WIRE_DONE, info.piece_num, single.dst_peer_id,
                         info.range_size, dur_ms=cost, t_ms=t_wire)
        if placed:
            _p2p_piece_bytes.observe(info.range_size)
        _p2p_pieces.labels("ok").inc()
        if self.verdicts is not None:
            self.verdicts.record_ok(single.dst_addr)
        await session.report_piece(self._piece_result(
            conductor, info, single.dst_peer_id, t0, ok=True, cost_ms=cost))
        return True

    def _note_corrupt(self, conductor, info: PieceInfo, parent_id: str,
                      addr: str = "", relayed: bool = False) -> bool:
        """A transfer failed digest verification at landing: count it
        (df_p2p_piece_total{result="corrupt"}), journal a flight event
        so dfdiag can name the corrupting parent, and record the hard
        verdict in the daemon-wide ledger — enough decayed corrupt
        verdicts locally shun the address for EVERY task on this daemon
        (scheduler reachable or not), journaled as a ``quarantine``
        flight event at the flip."""
        _p2p_pieces.labels("corrupt").inc()
        log.warning("piece %d from %s: digest mismatch (requeued)",
                    info.piece_num, parent_id[-12:])
        if conductor.flight is not None:
            conductor.flight.event(fr.CORRUPT, info.piece_num, parent_id,
                                   info.range_size)
        if self.verdicts is not None and addr:
            flipped = self.verdicts.record(addr, "corrupt",
                                           peer_id=parent_id,
                                           relayed=relayed)
            if flipped and conductor.flight is not None:
                conductor.flight.event(fr.QUARANTINE, info.piece_num, addr)
            return flipped
        return False

    @staticmethod
    def _fail_code(exc: DFError) -> str:
        """Typed verdict for a failed fetch (idl.FAIL_CODES): the
        downloader classifies transport failures at the raise site;
        digest mismatches are corrupt by definition."""
        code = getattr(exc, "fail_code", "")
        if code:
            return code
        return "corrupt" if exc.code == Code.CLIENT_DIGEST_MISMATCH \
            else "stall"

    _FAIL_EVENTS = {"stall": fr.STALL, "timeout": fr.TIMEOUT,
                    "refused": fr.REFUSED}

    def _note_fail(self, conductor, info: PieceInfo, parent_id: str,
                   addr: str, code: str) -> None:
        """Journal + ledger one NON-corrupt typed failure (corrupt goes
        through _note_corrupt): soft evidence — the ledger decays it for
        ordering, never shuns on it."""
        if conductor.flight is not None:
            kind = self._FAIL_EVENTS.get(code)
            if kind is not None:
                conductor.flight.event(kind, info.piece_num, parent_id)
        if self.verdicts is not None and addr and code != "corrupt":
            self.verdicts.record(addr, code, peer_id=parent_id)

    def _admissible(self, parent_id: str, addr: str) -> bool:
        """Parent admission gate: a locally-shunned address is refused a
        dispatcher slot no matter who offers it (scheduler packet, sync
        announcement, PEX rung) — the round trip of pulling, verifying,
        and requeuing a poisoned piece is exactly the waste the ledger
        exists to stop."""
        if self.verdicts is None or not self.verdicts.shunned(addr):
            return True
        log.info("refusing shunned parent %s (%s): local corrupt "
                 "verdicts", parent_id[-12:], addr)
        return False

    async def _pull_normal(self, conductor, session) -> bool:
        if session.result.content_length >= 0:
            conductor.set_content_info(session.result.content_length,
                                       session.result.piece_size)
        self.apply_shard_state(conductor)

        packet_task = asyncio.get_running_loop().create_task(
            self._consume_packets(conductor, session))
        workers = [asyncio.get_running_loop().create_task(
            self._worker(conductor, session)) for _ in range(self.parallelism)]
        try:
            # first gate: a parent must show up within the schedule timeout
            try:
                await asyncio.wait_for(self._first_parent.wait(),
                                       self.schedule_timeout_s)
            except asyncio.TimeoutError:
                log.info("no parents within %.1fs; back-source",
                         self.schedule_timeout_s)
                return False
            if self._need_back_source:
                return False

            # sessions without a scheduler behind them (the pex rung's
            # synthetic session, rescuable=False) must self-abort when the
            # swarm stops producing: with live-but-incomplete parents no
            # packet, verdict, or re-assignment is ever coming, so a stall
            # would otherwise tick forever (and a seed stuck here while
            # its leechers wait on IT is a pod-wide deadlock)
            rescuable = getattr(session, "rescuable", True)
            last_ready = len(conductor.ready)
            last_progress = time.monotonic()

            while True:
                if self._need_back_source:
                    return False
                if (conductor.total_pieces >= 0
                        and conductor.pieces_remaining() == 0):
                    # done = every NEEDED piece landed (the requested-shard
                    # subset for sharded tasks, all pieces otherwise). The
                    # commit flag is set in the SAME synchronous block as
                    # the coverage check: a widen (also loop-synchronous)
                    # either ran before it — and this check then saw the
                    # widened needed set and kept pulling — or is refused
                    # after it, so a completing subset can never be
                    # widened into "incomplete"
                    conductor._finishing = True
                    return True
                if not rescuable:
                    if len(conductor.ready) != last_ready:
                        last_ready = len(conductor.ready)
                        last_progress = time.monotonic()
                    elif (time.monotonic() - last_progress
                            > self.schedule_timeout_s):
                        log.info("scheduler-less pull stalled %.1fs at "
                                 "%d/%d pieces; returning to the ladder",
                                 self.schedule_timeout_s, last_ready,
                                 conductor.total_pieces)
                        return False
                # endgame gate: duplicate-request racing only for the task's
                # actual tail (see dispatcher._pick_endgame)
                remaining = conductor.pieces_remaining()
                self.dispatcher.endgame = (0 <= remaining <= ENDGAME_PIECES)
                if not self.dispatcher.has_live_parent():
                    # parents gone: give the scheduler a grace period to
                    # re-assign, then fall back to origin — the reschedule
                    # rung journals that this task is riding out an outage
                    if conductor.flight is not None:
                        conductor.flight.rung(fr.RUNG_RESCHEDULE)
                    try:
                        await asyncio.wait_for(
                            self._wait_parent_change(),
                            self.schedule_timeout_s)
                    except asyncio.TimeoutError:
                        log.info("parents exhausted; back-source for the rest")
                        return False
                    if conductor.flight is not None:
                        conductor.flight.rung(fr.RUNG_P2P)
                    continue
                # progress tick: piece arrivals notify the conductor's cond.
                # The acquire and the wait live in ONE wrapped coroutine so
                # wait_for's cancellation unwinds them atomically — a bare
                # wait_for(cond.wait(), t) splits them across tasks, and the
                # orphaned waiter can die holding the condition lock (the
                # same 3.10 hazard documented at the teardown below)
                try:
                    await asyncio.wait_for(self._piece_tick(conductor), 0.25)
                except asyncio.TimeoutError:
                    pass
        finally:
            # close the dispatcher BEFORE cancelling the workers, not just
            # before gathering them. Two distinct 3.10 asyncio hazards meet
            # here:
            #   * a cancel delivered in the same loop tick as a cond notify
            #     (the last piece's report) is swallowed by asyncio.wait_for
            #     (lost-cancellation), and the unbounded gather below then
            #     waits forever on an undead worker — with the dispatcher
            #     closed, such a worker's next get() returns None and it
            #     exits via the closed path;
            #   * cancelling a worker PARKED in get()'s wait_for(cond.wait)
            #     orphans the inner Condition.wait task, which re-acquires
            #     the condition lock in its finally and can die HOLDING it —
            #     a close() issued after that cancel then queues on the
            #     poisoned lock forever (the fake-pod silent-hang: conductor
            #     stuck in dispatcher.close, zero log output). Closing first
            #     lets close() take the lock while it is still healthy;
            #     workers then wake via the notify and exit cleanly, and the
            #     dispatcher's closed short-circuits keep any late caller
            #     off the lock entirely.
            await self.dispatcher.close()
            packet_task.cancel()
            for w in workers:
                w.cancel()
            await asyncio.gather(packet_task, *workers, return_exceptions=True)

    @staticmethod
    async def _piece_tick(conductor) -> None:
        async with conductor._piece_cond:
            await conductor._piece_cond.wait()

    async def _wait_parent_change(self) -> None:
        cond = self.dispatcher._cond
        async with cond:
            while (not self.dispatcher.has_live_parent()
                   and not self._need_back_source):
                await cond.wait()

    # ------------------------------------------------------------------

    async def _consume_packets(self, conductor, session) -> None:
        """Apply scheduler parent assignments as they arrive."""
        while True:
            packet: PeerPacket = await session.packets.get()
            code = Code(packet.code or 0)
            if code == Code.SCHED_NEED_BACK_SOURCE:
                self._need_back_source = True
                self._first_parent.set()
                async with self.dispatcher._cond:
                    self.dispatcher._cond.notify_all()
                return
            if code in (Code.SCHED_PEER_GONE, Code.SCHED_REREGISTER,
                        Code.SCHED_TASK_STATUS_ERROR, Code.UNAVAILABLE):
                # stream ended or scheduler lost us; workers drain what they
                # have, the main loop decides on fallback
                self._first_parent.set()
                continue
            parents = list(packet.candidate_peers or [])
            if packet.main_peer is not None:
                parents.insert(0, packet.main_peer)
            for parent in parents:
                if parent.peer_id == conductor.peer_id:
                    continue
                dl_addr = f"{parent.ip}:{parent.download_port}"
                if not self._admissible(parent.peer_id, dl_addr):
                    continue
                await self.dispatcher.add_parent(parent.peer_id, dl_addr,
                                                 resurrect=True,
                                                 is_seed=parent.is_seed,
                                                 link=parent.link)
                self._current_parents[parent.peer_id] = parent
                if self.peer_observer is not None:
                    self.peer_observer(parent)
                sync = self._synchronizers.get(parent.peer_id)
                if sync is None or (sync.task is not None and sync.task.done()):
                    sync = _Synchronizer(self, conductor, parent)
                    self._synchronizers[parent.peer_id] = sync
                    sync.start()
            if parents and not packet.advisory:
                # the packet is the scheduler's CURRENT parent assignment —
                # dropped parents release their upload slot server-side, so
                # continuing to pull from them would overload hosts the
                # scheduler is actively shedding (the round-robin that keeps
                # a loaded seed from serving every child rides on this).
                # Advisory packets (PEX swarm pre-population) skip the
                # prune: they add opportunistic parents without overriding
                # the scheduler's assignment.
                assigned = {p.peer_id for p in parents}
                for peer_id in list(self._synchronizers):
                    if peer_id not in assigned:
                        self._synchronizers.pop(peer_id).stop()
                        self._current_parents.pop(peer_id, None)
                        await self.dispatcher.remove_parent(peer_id)
            if parents:
                self._first_parent.set()

    async def _worker(self, conductor, session) -> None:
        while True:
            d = await self.dispatcher.get(timeout=0.1)
            if d is None:
                if self.dispatcher.closed:
                    return
                # idle worker with nothing dispatchable: pull-signal the
                # parents (super-seeding seeds ration announcements and
                # grow them on starvation pings — see rpcserver._SuperSeed)
                await self._maybe_ping()
                continue
            await self._download_one(conductor, session, d)

    async def _maybe_ping(self) -> None:
        if not self.dispatcher.starving():
            return
        now = time.monotonic()
        if now - self._last_ping < self._ping_interval:
            return
        self._last_ping = now
        announced = sum(p.announced
                        for p in self.dispatcher.parents.values())
        if announced > self._announced_at_ping:
            self._ping_interval = self._ping_base      # progress: re-arm
        else:
            self._ping_interval = min(self._ping_interval * 1.7, 1.2)
        self._announced_at_ping = announced
        for sync in list(self._synchronizers.values()):
            await sync.ping()
        # resurrect dead sync streams for parents the scheduler still
        # assigns us: a stream that failed at setup (connect refused under a
        # load spike) otherwise stays dead until the scheduler pushes a NEW
        # packet — and the sticky refresh only pushes on set-change, so a
        # stable assignment means no retry ever. This divergence is the
        # 100%-seed-sourced straggler: a child that lost its mesh at t=0 and
        # never got it back. Paced by the starvation gate above.
        for peer_id, parent in list(self._current_parents.items()):
            sync = self._synchronizers.get(peer_id)
            if sync is not None and sync.task is not None and sync.task.done():
                if not self._admissible(
                        peer_id, f"{parent.ip}:{parent.download_port}"):
                    continue
                if self.dispatcher.hard_removed(peer_id):
                    # lifetime fail cap: stays dead until the SCHEDULER
                    # re-offers it in a packet (its blocklists are the
                    # authority); auto-resurrecting here would loop a child
                    # against a corrupt parent forever
                    continue
                # the stream's failure path marked the parent removed in the
                # dispatcher — this is an explicit assignment-backed retry
                await self.dispatcher.add_parent(
                    peer_id, f"{parent.ip}:{parent.download_port}",
                    resurrect=True, is_seed=parent.is_seed,
                    link=parent.link)
                fresh = _Synchronizer(self, sync.conductor, parent)
                self._synchronizers[peer_id] = fresh
                fresh.start()

    async def _download_one(self, conductor, session, d: Dispatch) -> None:
        if conductor.swap_piece_nums and d.parent.is_seed:
            # a swap-class piece (a co-located replica's tree assignment)
            # riding the SEED: its swap hold expired — the partner died or
            # stalled and the tree is covering the hole (journaled so
            # dfdiag can tell this from a healthy swap)
            for info in d.pieces:
                if info.piece_num in conductor.swap_piece_nums:
                    conductor.note_shard_fallback(info.piece_num,
                                                  d.parent.peer_id)
        flight = conductor.flight
        if flight is not None:
            # worker pickup: queue_ms then measures the rate-limiter wait;
            # parent-side queueing lands in ttfb_ms (dispatched->first_byte)
            for info in d.pieces:
                flight.event(fr.SCHEDULED, info.piece_num, d.parent.peer_id)
        if conductor.rate_limiter is not None:
            await conductor.rate_limiter.acquire(d.size())
        t0 = int(time.time() * 1000)
        on_first = None
        if flight is not None:
            for info in d.pieces:
                flight.event(fr.DISPATCHED, info.piece_num, d.parent.peer_id)

            def on_first(_num=d.piece.piece_num, _pid=d.parent.peer_id):
                flight.event(fr.FIRST_BYTE, _num, _pid)
        from ..common import tracing
        try:
            with tracing.span("piece.download",
                              piece=d.piece.piece_num,
                              n_pieces=len(d.pieces),
                              parent=None,   # inherit the task span
                              ) as psp:
                psp.set(dst=d.parent.peer_id[-16:], link=int(d.parent.link))
                # watchdog section: a parent that wedges mid-transfer
                # self-reports (await-chain dump + SLO wire breach) well
                # before the hard per-piece deadline cancels the read
                # (no-op context while the plane is off); the deadline
                # scales with the group so healthy spans don't trip it
                with health.PLANE.watchdog.section(
                        "piece.wire",
                        health.PLANE.slo.section_deadline_s(len(d.pieces)),
                        stage="wire"):
                    span = self._relay_opener(conductor, d.pieces)
                    wire_meta: dict = {}
                    buf, cost = await self.downloader.download_span(
                        dst_addr=d.parent.addr, task_id=conductor.task_id,
                        src_peer_id=conductor.peer_id, pieces=d.pieces,
                        on_first_byte=on_first, relay_open=span,
                        qos_class=getattr(conductor, "qos_class", ""),
                        meta=wire_meta)
        except DFError as exc:
            if exc.code == Code.CLIENT_PEER_BUSY:
                # backpressure, not failure: requeue; no scheduler report
                # (a busy seed must not land on the blocklist)
                _p2p_pieces.labels("busy").inc()
                await self.dispatcher.report_busy(
                    d, retry_after_ms=getattr(exc, "retry_after_ms", 0))
                return
            _p2p_pieces.labels("fail").inc()
            log.debug("pieces %s from %s failed: %s",
                      [p.piece_num for p in d.pieces],
                      d.parent.peer_id[-12:], exc)
            fcode = self._fail_code(exc)
            # one transfer, one typed verdict (however many pieces rode
            # it) — per-piece ledger strikes would triple-count a single
            # dead connection
            self._note_fail(conductor, d.piece, d.parent.peer_id,
                            d.parent.addr, fcode)
            await self.dispatcher.report(d, ok=False)
            if d.parent.removed:
                # permanently removed (hard fail cap): its sync stream dies
                # too, or a dead parent keeps the engine looking alive
                # forever. Cooldown ejections keep their stream — the parent
                # keeps announcing and gets retried when the window expires.
                sync = self._synchronizers.get(d.parent.peer_id)
                if sync is not None:
                    sync.stop()
            for info in d.pieces:   # every group member failed, report each
                await session.report_piece(self._piece_result(
                    conductor, info, d.parent.peer_id, t0, ok=False,
                    code=exc.code, fail_code=fcode))
            return
        per_piece_cost = max(1, cost // len(d.pieces))
        # timestamp before the landing await, journaled only for pieces
        # that actually land — an endgame duplicate must not overwrite the
        # real deliverer's attribution
        t_wire = flight.now_ms() if flight is not None else 0.0
        try:
            # ONE landing hop for the whole span (storage write + verify
            # fused off-loop; HBM memcpy inline) — pre-PR5 this was one
            # to_thread + one hash pass + one write PER piece
            placed, corrupt, raced = await conductor.on_span_from_peer(
                d.parent.peer_id, d.pieces, buf, per_piece_cost)
        finally:
            # landing (including the sink's staging memcpy) has completed:
            # the buffer is recyclable — this kills the 4-16 MiB
            # alloc/free churn per download at fan-out. The relay span is
            # retired FIRST: its bytes now serve from storage (or, if a
            # piece failed verification, stop being servable at all)
            span.retire()
            POOL.release(buf)
        placed_set, corrupt_set = set(placed), set(corrupt)
        raced_set = set(raced)
        shun_flipped = False
        for info in d.pieces:
            if info.piece_num in corrupt_set:
                shun_flipped |= self._note_corrupt(
                    conductor, info, d.parent.peer_id, addr=d.parent.addr,
                    relayed=wire_meta.get("relayed", False))
                await session.report_piece(self._piece_result(
                    conductor, info, d.parent.peer_id, t0, ok=False,
                    code=Code.CLIENT_DIGEST_MISMATCH, fail_code="corrupt",
                    relayed=wire_meta.get("relayed", False)))
                continue
            if info.piece_num in raced_set:
                # an endgame racer is mid-landing: outcome unknown — say
                # nothing; the racer's own report settles the piece
                continue
            if info.piece_num in placed_set:
                if flight is not None:
                    flight.event(fr.WIRE_DONE, info.piece_num,
                                 d.parent.peer_id, info.range_size,
                                 dur_ms=per_piece_cost, t_ms=t_wire)
                _p2p_piece_bytes.observe(info.range_size)
            _p2p_pieces.labels("ok").inc()
            if self.verdicts is not None:
                self.verdicts.record_ok(d.parent.addr)
            await session.report_piece(self._piece_result(
                conductor, info, d.parent.peer_id, t0, ok=True,
                cost_ms=per_piece_cost, finished=len(conductor.ready)))
        if shun_flipped:
            # the ledger just shunned this address on local corrupt
            # evidence: sever it for THIS task immediately (permanent
            # removal + dead sync stream) — the admission gate keeps it
            # out of every later task, and the scheduler's pod-wide
            # quarantine follows from the corrupt reports above
            await self.dispatcher.remove_parent(d.parent.peer_id)
            sync = self._synchronizers.get(d.parent.peer_id)
            if sync is not None:
                sync.stop()
        await self.dispatcher.report(
            d, ok=True, cost_ms=cost,
            # a raced piece must NOT be marked done (the racer may yet
            # fail verification — it would be orphaned forever); leaving
            # it out requeues it, and the winner's report retires it
            completed=[info.piece_num for info in d.pieces
                       if info.piece_num not in corrupt_set
                       and info.piece_num not in raced_set])

    @staticmethod
    def _piece_result(conductor, info: PieceInfo, parent_id: str, t0: int, *,
                      ok: bool, cost_ms: int = 0, code: Code = Code.OK,
                      finished: int = 0, fail_code: str = "",
                      relayed: bool = False) -> PieceResult:
        reported = PieceInfo(piece_num=info.piece_num,
                             range_start=info.range_start,
                             range_size=info.range_size, digest=info.digest,
                             download_cost_ms=cost_ms)
        return PieceResult(
            task_id=conductor.task_id, src_peer_id=conductor.peer_id,
            dst_peer_id=parent_id, piece_info=reported, begin_ms=t0,
            end_ms=t0 + cost_ms, success=ok, code=int(code),
            fail_code=fail_code, relayed=relayed, finished_count=finished)

    # ------------------------------------------------------------------

    async def _teardown(self) -> None:
        for sync in self._synchronizers.values():
            sync.stop()
        await asyncio.gather(
            *(s.task for s in self._synchronizers.values() if s.task),
            return_exceptions=True)
        await self.dispatcher.close()
        if self._own_channels:
            await self._channels.close()
        if self._own_downloader:
            await self.downloader.close()
