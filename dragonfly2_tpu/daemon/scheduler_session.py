"""Scheduler connector: the daemon's client side of the scheduler service.

Role parity: reference ``client/daemon/peer/peertask_conductor.go`` register
(:249) + ``ReportPieceResult`` stream handling (:340, :659) and
``pkg/rpc/scheduler/client`` — one connector per daemon, one ``PeerSession``
per running task. The session owns the bidi report stream: piece results go
up, ``PeerPacket`` parent assignments come down into a queue the P2P engine
consumes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING

from ..common.errors import Code, DFError
from ..idl.messages import (Host, PeerPacket, PeerResult, PieceResult,
                            RegisterPeerTaskRequest, RegisterResult)
from ..rpc.client import Channel, ServiceClient

if TYPE_CHECKING:  # pragma: no cover
    from .conductor import PeerTaskConductor

log = logging.getLogger("df.flow.schedsess")

SCHEDULER_SERVICE = "df.scheduler.Scheduler"


class PeerSession:
    """A registered (task, peer) against one scheduler."""

    def __init__(self, client: ServiceClient, result: RegisterResult,
                 conductor: "PeerTaskConductor"):
        self.client = client
        self.result = result
        self.conductor = conductor
        self.task_id = conductor.task_id
        self.peer_id = conductor.peer_id
        self.packets: asyncio.Queue[PeerPacket] = asyncio.Queue()
        self._stream = None
        self._out: asyncio.Queue = asyncio.Queue()
        self._writer: asyncio.Task | None = None
        self._reader: asyncio.Task | None = None
        self._closed = False
        self._peer_result_sent = False

    _EOF = object()

    async def open_report_stream(self) -> None:
        """Open the bidi piece-result stream; an empty first report asks the
        scheduler for the initial parent assignment (reference sends a zeroed
        PieceResult the same way)."""
        self._stream = self.client.stream_stream("ReportPieceResult")
        await self._stream.write(PieceResult(
            task_id=self.task_id, src_peer_id=self.peer_id, success=True,
            code=int(Code.OK)))
        loop = asyncio.get_running_loop()
        self._reader = loop.create_task(self._read_loop())
        self._writer = loop.create_task(self._write_loop())

    async def _write_loop(self) -> None:
        """Sole owner of the stream's write half. grpc.aio allows one
        outstanding write, and a write cancelled mid-flight (worker teardown)
        poisons the stream so done_writing never completes — so piece
        workers enqueue and only this task ever touches the stream."""
        try:
            while True:
                item = await self._out.get()
                if item is self._EOF:
                    await self._stream.done_writing()
                    return
                await self._stream.write(item)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - stream went away
            log.debug("report write loop ended: %s", exc)

    async def _read_loop(self) -> None:
        try:
            while True:
                packet = await self._stream.read()
                if packet is None:
                    break
                self.packets.put_nowait(packet)
        except DFError as exc:
            # surface scheduler-side verdicts (NeedBackSource et al.) as a
            # synthetic packet so the engine's single consume loop sees them
            self.packets.put_nowait(PeerPacket(
                task_id=self.task_id, src_peer_id=self.peer_id,
                code=int(exc.code)))
        except Exception as exc:  # noqa: BLE001 - stream teardown races
            if not self._closed:
                log.debug("report stream reader ended: %s", exc)
        finally:
            self.packets.put_nowait(PeerPacket(
                task_id=self.task_id, src_peer_id=self.peer_id,
                code=int(Code.UNAVAILABLE)))

    async def report_piece(self, result: PieceResult) -> None:
        if self._stream is None or self._closed:
            return
        if self._writer is not None and self._writer.done():
            # writer died (scheduler went away): don't queue into the void
            log.debug("report_piece dropped: writer gone")
            return
        self._out.put_nowait(result)

    async def _drain_task(self, task: asyncio.Task | None,
                          timeout: float) -> None:
        if task is None or task.done():
            return
        try:
            await asyncio.wait_for(asyncio.shield(task), timeout)
        except (asyncio.TimeoutError, Exception):  # noqa: BLE001
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def close(self, *, success: bool) -> None:
        if self._closed:
            return
        self._closed = True
        conductor = self.conductor
        if self._stream is not None:
            # graceful half-close: queued piece results drain first, then the
            # writer sends EOF; the reader ends when the scheduler finishes
            # its side. Cancelling instead of draining would lose the last
            # reports and the scheduler would never see this peer complete.
            self._out.put_nowait(self._EOF)
            await self._drain_task(self._writer, 5.0)
            await self._drain_task(self._reader, 5.0)
            self._stream.cancel()
        if conductor is not None and not self._peer_result_sent:
            self._peer_result_sent = True
            flight = getattr(conductor, "flight", None)
            try:
                await self.client.unary("ReportPeerResult", PeerResult(
                    task_id=self.task_id, peer_id=self.peer_id,
                    url=conductor.url, success=success,
                    traffic=conductor.traffic_p2p,
                    cost_ms=int(time.time() * 1000) - conductor.start_ms,
                    code=int(conductor.fail_code),
                    total_piece_count=conductor.total_pieces,
                    content_length=conductor.content_length,
                    flight_summary=(flight.compact_summary()
                                    if flight is not None else None)),
                    timeout=5.0)
            except Exception as exc:  # noqa: BLE001
                log.debug("ReportPeerResult failed: %s", exc)


class SchedulerConnector:
    """Daemon-wide scheduler client; conductor-facing ``register`` entry.

    The conductor treats ``register`` raising SCHED_NEED_BACK_SOURCE /
    UNAVAILABLE / DEADLINE_EXCEEDED as "go to origin" (the reference's
    fallback ladder at ``peertask_conductor.go:284``).
    """

    def __init__(self, addresses: list[str], host: Host, *,
                 register_timeout_s: float = 10.0):
        from ..rpc.balancer import HashRing
        self.addresses = list(addresses)
        self.host = host
        self.register_timeout_s = register_timeout_s
        self._ring = HashRing(self.addresses)
        self._channels: dict[str, Channel] = {}
        self._close_tasks: set = set()   # strong refs: the loop only
        # weak-refs tasks, and a GC'd close task leaks its channel

    def update_addresses(self, addresses: list[str]) -> None:
        """Adopt a refreshed scheduler set (manager dynconfig): new
        addresses join the consistent-hash ring; removed ones leave it
        and their channels CLOSE — a scheduler the manager dropped is
        gone or being retired, and sessions riding it take the
        conductor's normal reschedule ladder (stream-loss recovery is
        already first-class, see tests/test_churn.py). New tasks hash
        onto the new ring immediately."""
        want = set(addresses)
        have = set(self.addresses)
        if want == have:
            return
        import asyncio
        for addr in want - have:
            self._ring.add(addr)
        for addr in have - want:
            self._ring.remove(addr)
            ch = self._channels.pop(addr, None)
            if ch is not None:
                t = asyncio.get_running_loop().create_task(ch.close())
                self._close_tasks.add(t)
                t.add_done_callback(self._close_tasks.discard)
        self.addresses = list(addresses)

    def _client(self, task_id: str) -> ServiceClient:
        # consistent-hash the task onto one scheduler address so all peers of
        # a task converge on the same brain (reference pkg/balancer)
        addr = self._ring.pick(task_id)
        if addr is None:
            raise DFError(Code.UNAVAILABLE, "no scheduler addresses")
        ch = self._channels.get(addr)
        if ch is None:
            ch = Channel(addr)
            self._channels[addr] = ch
        return ServiceClient(ch, SCHEDULER_SERVICE)

    def refresh_host(self, host: Host) -> None:
        self.host = host

    async def register(self, conductor: "PeerTaskConductor") -> PeerSession:
        client = self._client(conductor.task_id)
        result: RegisterResult = await client.unary(
            "RegisterPeerTask",
            RegisterPeerTaskRequest(
                url=conductor.url, url_meta=conductor.url_meta,
                task_id=conductor.task_id, peer_id=conductor.peer_id,
                peer_host=self.host),
            timeout=self.register_timeout_s)
        # adopt the scheduler's application-table resolution only when it
        # actually resolved something: an older scheduler echoes the
        # LEVEL0 default, which must not clobber an explicit local value
        if int(result.resolved_priority) != 0:
            conductor.resolved_priority = int(result.resolved_priority)
        session = PeerSession(client, result, conductor)
        await session.open_report_stream()
        return session

    async def announce_host(self, request) -> None:
        if not self.addresses:
            return
        client = self._client(self.host.id)
        await client.unary("AnnounceHost", request, timeout=5.0)

    async def sync_probes(self):
        """Open the probe bidi stream (network-topology module drives it)."""
        client = self._client(self.host.id)
        return client.stream_stream("SyncProbes")

    async def leave_host(self) -> None:
        from ..idl.messages import LeaveHostRequest
        try:
            client = self._client(self.host.id)
            await client.unary("LeaveHost",
                               LeaveHostRequest(host_id=self.host.id),
                               timeout=3.0)
        except Exception as exc:  # noqa: BLE001 - best effort on shutdown
            log.debug("LeaveHost failed: %s", exc)

    async def close(self) -> None:
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
