"""Scheduler connector: the daemon's client side of the scheduler service.

Role parity: reference ``client/daemon/peer/peertask_conductor.go`` register
(:249) + ``ReportPieceResult`` stream handling (:340, :659) and
``pkg/rpc/scheduler/client`` — one connector per daemon, one ``PeerSession``
per running task. The session owns the bidi report stream: piece results go
up, ``PeerPacket`` parent assignments come down into a queue the P2P engine
consumes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import TYPE_CHECKING

from ..common import faultgate
from ..common.errors import Code, DFError
from ..common.metrics import REGISTRY
from ..common.retry import Retrier, RetryPolicy
from ..idl.messages import (Host, PeerPacket, PeerResult, PieceResult,
                            RegisterPeerTaskRequest, RegisterResult)
from ..rpc.client import Channel, RPCError, ServiceClient
from . import flight_recorder as fr

if TYPE_CHECKING:  # pragma: no cover
    from .conductor import PeerTaskConductor

log = logging.getLogger("df.flow.schedsess")

SCHEDULER_SERVICE = "df.scheduler.Scheduler"

_report_dropped = REGISTRY.counter(
    "df_sched_report_dropped_total",
    "piece results dropped because the scheduler report stream died")

# terminal PeerResult / AnnounceHost: one retry with backoff before giving
# up — a lost terminal report makes the scheduler hold a ghost peer until
# GC, which is worth one more try but not worth stalling shutdown
_REPORT_RETRY = RetryPolicy(max_attempts=2, base_s=0.3, max_s=1.0,
                            budget_s=8.0)

# register transport failures that mean "this scheduler, not this task":
# the ladder moves to the next ring member instead of going to origin
_FAILOVER_CODES = (Code.UNAVAILABLE, Code.DEADLINE_EXCEEDED)


class PeerSession:
    """A registered (task, peer) against one scheduler."""

    def __init__(self, client: ServiceClient, result: RegisterResult,
                 conductor: "PeerTaskConductor"):
        self.client = client
        self.result = result
        self.conductor = conductor
        self.task_id = conductor.task_id
        self.peer_id = conductor.peer_id
        self.packets: asyncio.Queue[PeerPacket] = asyncio.Queue()
        self._stream = None
        self._out: asyncio.Queue = asyncio.Queue()
        self._writer: asyncio.Task | None = None
        self._reader: asyncio.Task | None = None
        self._closed = False
        self._peer_result_sent = False

    _EOF = object()

    async def open_report_stream(self) -> None:
        """Open the bidi piece-result stream; an empty first report asks the
        scheduler for the initial parent assignment (reference sends a zeroed
        PieceResult the same way)."""
        self._stream = self.client.stream_stream("ReportPieceResult")
        await self._stream.write(PieceResult(
            task_id=self.task_id, src_peer_id=self.peer_id, success=True,
            code=int(Code.OK)))
        loop = asyncio.get_running_loop()
        self._reader = loop.create_task(self._read_loop())
        self._writer = loop.create_task(self._write_loop())

    async def _write_loop(self) -> None:
        """Sole owner of the stream's write half. grpc.aio allows one
        outstanding write, and a write cancelled mid-flight (worker teardown)
        poisons the stream so done_writing never completes — so piece
        workers enqueue and only this task ever touches the stream."""
        try:
            while True:
                item = await self._out.get()
                if item is self._EOF:
                    await self._stream.done_writing()
                    return
                await self._stream.write(item)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - stream went away
            log.debug("report write loop ended: %s", exc)

    async def _read_loop(self) -> None:
        try:
            while True:
                packet = await self._stream.read()
                if packet is None:
                    break
                self.packets.put_nowait(packet)
        except DFError as exc:
            # surface scheduler-side verdicts (NeedBackSource et al.) as a
            # synthetic packet so the engine's single consume loop sees them
            self.packets.put_nowait(PeerPacket(
                task_id=self.task_id, src_peer_id=self.peer_id,
                code=int(exc.code)))
        except Exception as exc:  # noqa: BLE001 - stream teardown races
            if not self._closed:
                log.debug("report stream reader ended: %s", exc)
        finally:
            self.packets.put_nowait(PeerPacket(
                task_id=self.task_id, src_peer_id=self.peer_id,
                code=int(Code.UNAVAILABLE)))

    async def report_piece(self, result: PieceResult) -> None:
        if self._stream is None or self._closed:
            return
        if self._writer is not None and self._writer.done():
            # writer died (scheduler went away): don't queue into the void —
            # but COUNT it; silent drops leave the scheduler believing this
            # peer never made progress (ghost-peer GC), and the count rides
            # the flight summary so dfdiag surfaces it
            _report_dropped.inc()
            flight = getattr(self.conductor, "flight", None)
            if flight is not None:
                flight.report_drops += 1
            log.debug("report_piece dropped: writer gone")
            return
        self._out.put_nowait(result)

    async def _drain_task(self, task: asyncio.Task | None,
                          timeout: float) -> None:
        if task is None or task.done():
            return
        try:
            await asyncio.wait_for(asyncio.shield(task), timeout)
        except (asyncio.TimeoutError, Exception):  # noqa: BLE001
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def close(self, *, success: bool) -> None:
        if self._closed:
            return
        self._closed = True
        conductor = self.conductor
        if self._stream is not None:
            # graceful half-close: queued piece results drain first, then the
            # writer sends EOF; the reader ends when the scheduler finishes
            # its side. Cancelling instead of draining would lose the last
            # reports and the scheduler would never see this peer complete.
            self._out.put_nowait(self._EOF)
            await self._drain_task(self._writer, 5.0)
            await self._drain_task(self._reader, 5.0)
            self._stream.cancel()
        if conductor is not None and not self._peer_result_sent:
            self._peer_result_sent = True
            flight = getattr(conductor, "flight", None)
            result = PeerResult(
                task_id=self.task_id, peer_id=self.peer_id,
                url=conductor.url, success=success,
                traffic=conductor.traffic_p2p,
                cost_ms=int(time.time() * 1000) - conductor.start_ms,
                code=int(conductor.fail_code),
                total_piece_count=conductor.total_pieces,
                content_length=conductor.content_length,
                flight_summary=(flight.compact_summary()
                                if flight is not None else None))
            try:
                # retried once with backoff: losing the TERMINAL report
                # leaves the scheduler holding a ghost peer until GC. The
                # outer Retrier is the ONLY retry layer (max_attempts=1
                # client) — stacking it on the default 3-attempt client
                # would burn the whole budget inside attempt one on a
                # black-holed scheduler and never actually re-send
                once = ServiceClient(self.client.channel, SCHEDULER_SERVICE,
                                     max_attempts=1)
                await Retrier(_REPORT_RETRY).run(
                    lambda: once.unary("ReportPeerResult", result,
                                       timeout=5.0),
                    retryable=lambda exc: not isinstance(exc, DFError)
                    or exc.code in _FAILOVER_CODES)
            except Exception as exc:  # noqa: BLE001
                log.debug("ReportPeerResult failed: %s", exc)


class SchedulerConnector:
    """Daemon-wide scheduler client; conductor-facing ``register`` entry.

    The conductor treats ``register`` raising SCHED_NEED_BACK_SOURCE /
    UNAVAILABLE / DEADLINE_EXCEEDED as "go to origin" (the reference's
    fallback ladder at ``peertask_conductor.go:284``) — but UNAVAILABLE is
    now a LAST resort: a dead hashed scheduler first fails over to the
    next ``failover_n`` ring members, and the dead address is stickily
    demoted so subsequent tasks skip it until the ``demote_s`` window
    expires (at which point the next task probes it and either revives it
    or re-demotes). One dead scheduler must not send every task hashed to
    it to origin while healthy ring members sit idle.
    """

    def __init__(self, addresses: list[str], host: Host, *,
                 register_timeout_s: float = 10.0, failover_n: int = 3,
                 demote_s: float = 30.0):
        from ..rpc.balancer import HashRing
        self.addresses = list(addresses)
        self.host = host
        self.register_timeout_s = register_timeout_s
        self.failover_n = max(1, failover_n)
        self.demote_s = demote_s
        self._ring = HashRing(self.addresses)
        self._channels: dict[str, Channel] = {}
        self._demoted: dict[str, float] = {}   # addr -> monotonic revive time
        self._close_tasks: set = set()   # strong refs: the loop only
        # weak-refs tasks, and a GC'd close task leaks its channel
        # scheduler-epoch watermark (recovery reconciliation): register
        # results and announce responses carry the serving scheduler's
        # boot epoch; a CHANGE means the brain restarted with at best a
        # snapshot of what this daemon holds — the announcer drains
        # reconcile_event and replays held content (AnnounceContent)
        self._epoch = 0
        self.reconcile_event = asyncio.Event()

    def update_addresses(self, addresses: list[str]) -> None:
        """Adopt a refreshed scheduler set (manager dynconfig): new
        addresses join the consistent-hash ring; removed ones leave it
        and their channels CLOSE — a scheduler the manager dropped is
        gone or being retired, and sessions riding it take the
        conductor's normal reschedule ladder (stream-loss recovery is
        already first-class, see tests/test_churn.py). New tasks hash
        onto the new ring immediately."""
        want = set(addresses)
        have = set(self.addresses)
        if want == have:
            return
        import asyncio
        for addr in want - have:
            self._ring.add(addr)
        for addr in have - want:
            self._ring.remove(addr)
            self._demoted.pop(addr, None)
            ch = self._channels.pop(addr, None)
            if ch is not None:
                t = asyncio.get_running_loop().create_task(ch.close())
                self._close_tasks.add(t)
                t.add_done_callback(self._close_tasks.discard)
        self.addresses = list(addresses)

    # -- scheduler epoch (recovery reconciliation) ---------------------

    def note_epoch(self, epoch: int) -> bool:
        """Record the serving scheduler's boot epoch. Returns True (and
        wakes the announcer's reconcile wait) when a previously-seen
        epoch CHANGED — the brain restarted and must relearn who holds
        what. First contact is not a change: the announcer's initial
        content announce covers the daemon-restart direction."""
        if not epoch or epoch == self._epoch:
            return False
        first = self._epoch == 0
        self._epoch = epoch
        if first:
            return False
        self.reconcile_event.set()
        return True

    def mark_reconcile(self) -> None:
        """Force a content re-announce (register ring failover: the
        successor member may have imported only a handoff summary)."""
        self.reconcile_event.set()

    # -- demotion (sticky failover memory) -----------------------------

    def _alive(self, addr: str) -> bool:
        until = self._demoted.get(addr)
        if until is None:
            return True
        if time.monotonic() >= until:
            # probe window: eligible again; the next register against it
            # either revives it for real or re-demotes it
            self._demoted.pop(addr, None)
            return True
        return False

    def demote(self, addr: str) -> None:
        self._demoted[addr] = time.monotonic() + self.demote_s
        log.info("scheduler %s demoted for %.1fs", addr, self.demote_s)

    def revive(self, addr: str) -> None:
        if self._demoted.pop(addr, None) is not None:
            log.info("scheduler %s revived", addr)

    def demoted(self) -> set[str]:
        return {a for a in list(self._demoted) if not self._alive(a)}

    async def probe_demoted(self, *, timeout_s: float = 2.0) -> list[str]:
        """Actively probe every stickily-demoted ring member with a TCP
        connect; revive the ones that answer. Returns the revived list.

        Closes the latent revival gap: ``_alive`` only re-admits a demoted
        address when some task's register happens to consult it AFTER the
        demote window — a daemon with no register traffic (or whose tasks
        all hash elsewhere) would sit on the pex/back_source rungs long
        after the scheduler healed. The PEX gossip ticker (daemon/pex.py)
        rides this on every round. A connect-level probe is deliberately
        cheap and optimistic: a revived-but-still-sick member is re-demoted
        by the next register that actually exercises it."""
        async def probe(addr: str) -> str | None:
            host, _, port = addr.rpartition(":")
            if not host or not port.isdigit():
                return None
            try:
                _r, w = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port)), timeout_s)
            except (OSError, asyncio.TimeoutError):
                return None
            w.close()
            try:
                await w.wait_closed()
            except OSError:
                pass
            return addr

        # concurrent: with the whole ring down (exactly when the caller —
        # the PEX ticker — matters most) serial probes would stall the
        # gossip round by timeout_s PER dead member
        results = await asyncio.gather(*(probe(a)
                                         for a in list(self._demoted)))
        revived = [a for a in results if a is not None]
        for addr in revived:
            self.revive(addr)
        return revived

    def export_demotions(self) -> dict:
        """Persistable demotion memory: remaining seconds per demoted
        member (monotonic stamps don't survive a process). A restarted
        dfdaemon that forgot its demotions would re-probe every dead
        scheduler on its first task and pay the register timeout ladder
        all over again — the exact sticky-memory this set exists for."""
        now = time.monotonic()
        return {"v": 1,
                "demoted": {a: round(t - now, 3)
                            for a, t in self._demoted.items() if t > now}}

    def restore_demotions(self, state: dict | None) -> int:
        """Re-arm demotions from a prior process. Refusal is wholesale
        (schema guard); each entry's remaining window is clamped to
        ``demote_s`` — a clock-skewed or hand-edited blob must not demote
        a member for hours — and members no longer in the address set are
        dropped."""
        if not isinstance(state, dict) or state.get("v") != 1:
            return 0
        now = time.monotonic()
        known = set(self.addresses)
        n = 0
        for addr, remaining in (state.get("demoted") or {}).items():
            try:
                rem = min(float(remaining), self.demote_s)
            except (TypeError, ValueError):
                continue
            if rem <= 0 or addr not in known:
                continue
            self._demoted[addr] = now + rem
            n += 1
        if n:
            log.info("restored %d demoted scheduler(s) from prior run", n)
        return n

    def _candidates(self, key: str) -> list[str]:
        """Failover order for ``key``: the next-N distinct ring members
        clockwise from the key's hash, live ones first; demoted addresses
        stay listed LAST — with every candidate demoted, trying a dead
        scheduler still beats silently going to origin."""
        cands = self._ring.pick_n(key, self.failover_n)
        live = [a for a in cands if self._alive(a)]
        return live + [a for a in cands if a not in live]

    def _client_at(self, addr: str, *, max_attempts: int = 3) -> ServiceClient:
        ch = self._channels.get(addr)
        if ch is None:
            ch = Channel(addr)
            self._channels[addr] = ch
        return ServiceClient(ch, SCHEDULER_SERVICE,
                             max_attempts=max_attempts)

    def _client(self, task_id: str) -> ServiceClient:
        # consistent-hash the task onto one scheduler address so all peers of
        # a task converge on the same brain (reference pkg/balancer),
        # skipping stickily-demoted members
        cands = self._candidates(task_id)
        if not cands:
            raise DFError(Code.UNAVAILABLE, "no scheduler addresses")
        return self._client_at(cands[0])

    def refresh_host(self, host: Host) -> None:
        self.host = host

    async def register(self, conductor: "PeerTaskConductor") -> PeerSession:
        """Register around the ring: the hashed scheduler first, then the
        next ring members (``failover_n`` total) before raising UNAVAILABLE
        and sending the conductor to origin. Transport-dead members are
        demoted; scheduler VERDICTS (NeedBackSource, Forbidden...) always
        propagate from whichever member answered."""
        cands = self._candidates(conductor.task_id)
        if not cands:
            raise DFError(Code.UNAVAILABLE, "no scheduler addresses")
        flight = getattr(conductor, "flight", None)
        request = RegisterPeerTaskRequest(
            url=conductor.url, url_meta=conductor.url_meta,
            task_id=conductor.task_id, peer_id=conductor.peer_id,
            peer_host=self.host)
        last_exc: BaseException | None = None
        for i, addr in enumerate(cands):
            # one attempt per member: in-place retries against a dead
            # address only delay the healthy one clockwise of it
            client = self._client_at(addr, max_attempts=1)
            try:
                if faultgate.ARMED:
                    # bounded by the register timeout so a 'hang' script
                    # walks the same deadline -> failover path a wedged
                    # scheduler would (TimeoutError is caught below)
                    await asyncio.wait_for(
                        faultgate.fire("sched.register", key=addr),
                        self.register_timeout_s)
                result: RegisterResult = await client.unary(
                    "RegisterPeerTask", request,
                    timeout=self.register_timeout_s)
            except DFError as exc:
                if exc.code not in _FAILOVER_CODES:
                    raise          # a verdict, not a dead scheduler
                self.demote(addr)
                last_exc = exc
                log.warning("register on %s failed (%s); trying next ring "
                            "member", addr, exc.code.name)
                continue
            except (RPCError, OSError, asyncio.TimeoutError) as exc:
                self.demote(addr)
                last_exc = exc
                log.warning("register on %s failed (%s); trying next ring "
                            "member", addr, exc)
                continue
            self.revive(addr)
            self.note_epoch(int(getattr(result, "scheduler_epoch", 0)))
            if i > 0:
                if flight is not None:
                    flight.rung(fr.RUNG_RING_FAILOVER)
                # the member clockwise of a dead one starts from at most
                # a manager-relayed summary: replay held content at it
                self.mark_reconcile()
            # adopt the scheduler's application-table resolution only when
            # it actually resolved something: an older scheduler echoes the
            # LEVEL0 default, which must not clobber an explicit local value
            if int(result.resolved_priority) != 0:
                conductor.resolved_priority = int(result.resolved_priority)
            # the session keeps the default retrying client: its unaries
            # (ReportPeerResult) talk to a member that just answered
            session = PeerSession(self._client_at(addr), result, conductor)
            await session.open_report_stream()
            return session
        raise DFError(
            Code.UNAVAILABLE,
            f"all {len(cands)} scheduler ring members unreachable "
            f"(last: {last_exc})")

    async def announce_host(self, request):
        if not self.addresses:
            return None
        cands = self._candidates(self.host.id)
        if not cands:
            raise DFError(Code.UNAVAILABLE, "no scheduler addresses")
        # single retry layer, same rationale as ReportPeerResult above
        client = self._client_at(cands[0], max_attempts=1)
        resp = await Retrier(_REPORT_RETRY).run(
            lambda: client.unary("AnnounceHost", request, timeout=5.0),
            retryable=lambda exc: not isinstance(exc, DFError)
            or exc.code in _FAILOVER_CODES)
        # older scheduler answers Empty (epoch 0 -> ignored by note_epoch)
        self.note_epoch(int(getattr(resp, "scheduler_epoch", 0)))
        return resp

    async def announce_content(self, request):
        """Replay held content at the hashed scheduler (recovery
        reconciliation). Same single-retry envelope as announce_host —
        a brain that stays away gets the replay on the next announce
        interval instead."""
        if not self.addresses:
            return None
        cands = self._candidates(self.host.id)
        if not cands:
            raise DFError(Code.UNAVAILABLE, "no scheduler addresses")
        client = self._client_at(cands[0], max_attempts=1)
        resp = await Retrier(_REPORT_RETRY).run(
            lambda: client.unary("AnnounceContent", request, timeout=10.0),
            retryable=lambda exc: not isinstance(exc, DFError)
            or exc.code in _FAILOVER_CODES)
        self.note_epoch(int(getattr(resp, "scheduler_epoch", 0)))
        return resp

    async def sync_probes(self):
        """Open the probe bidi stream (network-topology module drives it)."""
        client = self._client(self.host.id)
        return client.stream_stream("SyncProbes")

    async def leave_host(self) -> None:
        from ..idl.messages import LeaveHostRequest
        try:
            client = self._client(self.host.id)
            await client.unary("LeaveHost",
                               LeaveHostRequest(host_id=self.host.id),
                               timeout=3.0)
        except Exception as exc:  # noqa: BLE001 - best effort on shutdown
            log.debug("LeaveHost failed: %s", exc)

    async def close(self) -> None:
        # drain the channel-close tasks update_addresses spawned: left
        # running they can outlive the loop and leak (or close) channels
        # after teardown
        if self._close_tasks:
            await asyncio.gather(*list(self._close_tasks),
                                 return_exceptions=True)
            self._close_tasks.clear()
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
