"""Network-topology prober: measure RTTs to scheduler-chosen hosts.

Role parity: reference ``client/daemon/networktopology/network_topology.go``
— a ``SyncProbes`` bidi stream: the scheduler hands out probe targets, the
daemon measures RTT and reports. The reference ICMP-pings; here RTT is a
TCP connect to the target's daemon port (no raw-socket privilege needed,
and it measures the path the pieces will actually take).
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..idl.messages import Probe, SyncProbesRequest

log = logging.getLogger("df.flow.nettopo")

CONNECT_TIMEOUT_S = 2.0


async def tcp_rtt_us(ip: str, port: int) -> int | None:
    t0 = time.monotonic()
    try:
        _r, w = await asyncio.wait_for(
            asyncio.open_connection(ip, port), CONNECT_TIMEOUT_S)
    except (OSError, asyncio.TimeoutError):
        return None
    rtt = int((time.monotonic() - t0) * 1e6)
    w.close()
    try:
        await w.wait_closed()
    except OSError:
        pass
    return rtt


class NetworkTopologyProber:
    def __init__(self, daemon):
        self.daemon = daemon
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self._probe_round()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - scheduler may be away
                log.debug("probe round failed: %s", exc)
            # pace re-dials even when the scheduler closes the stream cleanly
            await asyncio.sleep(20.0)

    async def _probe_round(self) -> None:
        stream = await self.daemon.scheduler.sync_probes()
        try:
            interval_s = 20.0
            while True:
                # ask for targets
                await stream.write(SyncProbesRequest(
                    host=self.daemon.host_info()))
                resp = await stream.read()
                if resp is None:
                    return
                interval_s = resp.probe_interval_s or interval_s
                probes: list[Probe] = []
                failed: list[str] = []
                for target in resp.targets or []:
                    rtt = await tcp_rtt_us(target.ip, target.port)
                    if rtt is None:
                        failed.append(target.host_id)
                    else:
                        probes.append(Probe(
                            target_host_id=target.host_id, rtt_us=rtt,
                            created_at_ms=int(time.time() * 1000)))
                if probes or failed:
                    # report promptly — the nt evaluator is only as fresh as
                    # the last report; the pacing sleep still bounds load
                    await stream.write(SyncProbesRequest(
                        host=self.daemon.host_info(),
                        probes=probes or None,
                        failed_host_ids=failed or None))
                    if await stream.read() is None:
                        return
                await asyncio.sleep(interval_s)
        finally:
            stream.cancel()

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
