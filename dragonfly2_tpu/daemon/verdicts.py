"""Per-parent verdict ledger: the daemon's local half of the swarm
immune system.

Role parity: none in the reference — Dragonfly2 catches a corrupt piece
at the child's landing, silently requeues it, and will happily pull from
(or be steered back at) the same poisoner forever; the only long-term
ejector is the scheduler's statistical slowness check, which a *lying*
parent never trips. This module gives every daemon a decayed, typed
memory of how each parent has behaved, consulted locally by the piece
engine (parent admission), the PEX rung (holder filtering/ordering), and
relay parent choice — so a parent that served corruption is shunned even
when no scheduler is reachable.

Evidence rules (the anti-slander contract, docs/RESILIENCE.md):

* **local verdicts quarantine** — only failures THIS daemon verified
  first-hand (``record``) can shun a parent. ``corrupt`` is hard
  evidence (the bytes landed and failed the digest check: not
  congestion, not load); ``SHUN_THRESHOLD`` decayed corrupt verdicts
  flip the parent to locally shunned.
* **gossip hints only deprioritize** — a PEX digest claiming some third
  party served corruption (``hint``) may move that party to the back of
  the parent ordering, never off it. Accepting remote accusations as
  shunning evidence would let one byzantine gossiper evict any honest
  host from the whole pod with a forged digest.
* **self-quarantine** — when the daemon's OWN storage fails
  re-verification (boot reload re-hash, content-store placement
  re-hash), it is the poisoner: it stops advertising tasks in PEX
  digests and flags its AnnounceHost/register ``Host.quarantined`` so
  the scheduler excludes it pod-wide. Sticky for the process lifetime —
  bit-rot does not heal without operator action, and a restart re-runs
  the boot re-verify that clears it.

Counters use half-life decay on an injectable clock so a genuinely
repaired parent works its way back (the scheduler's probation ladder is
the authoritative reprieve path; this ledger just stops re-shunning once
the evidence has decayed).
"""

from __future__ import annotations

import logging
import time
from typing import Callable

from ..common.metrics import REGISTRY
from ..idl.messages import FAIL_CODES

log = logging.getLogger("df.flow.verdicts")

_verdicts = REGISTRY.counter(
    "df_verdict_total",
    "typed piece-failure verdicts recorded against parents, by the "
    "FAIL_CODES vocabulary", ("code",))
_hints = REGISTRY.counter(
    "df_verdict_hints_total",
    "third-party corruption accusations received over PEX gossip "
    "(anti-slander: these deprioritize, never shun)")
_shunned_gauge = REGISTRY.gauge(
    "df_verdict_shunned_parents",
    "parent addresses this daemon currently shuns on local corrupt "
    "verdicts")
_selfq_gauge = REGISTRY.gauge(
    "df_verdict_self_quarantined",
    "1 while this daemon has self-quarantined after detecting its own "
    "storage bit-rot")

# decayed local corrupt verdicts at which a parent flips to shunned —
# deliberately small: corruption is verified evidence, and every further
# transfer from the parent is wasted wire bytes plus a re-pull
SHUN_THRESHOLD = 2.0
# a single decayed local corrupt verdict (or any gossip hint) is enough
# to DEPRIORITIZE: order the parent behind clean holders without
# excluding it
SUSPECT_THRESHOLD = 0.75


class _Parent:
    """Decayed per-code failure counters + bookkeeping for one parent
    address."""

    __slots__ = ("codes", "relayed_corrupt", "at", "ok", "peer_ids",
                 "hinted_at")

    def __init__(self) -> None:
        self.codes: dict[str, float] = {}
        # corrupt verdicts on CUT-THROUGH transfers (X-DF-Relay), decayed
        # on the same clock: circumstantial — the bytes originated
        # upstream of the relay — so this mass deprioritizes, never shuns
        self.relayed_corrupt = 0.0
        self.at = 0.0
        self.ok = 0
        self.peer_ids: set[str] = set()      # recent peer ids at this addr
        self.hinted_at: float | None = None  # last gossip accusation

    def decay(self, now: float, halflife_s: float) -> None:
        if (not self.codes and not self.relayed_corrupt) \
                or halflife_s <= 0:
            self.at = now
            return
        factor = 0.5 ** (max(now - self.at, 0.0) / halflife_s)
        self.codes = {c: v * factor for c, v in self.codes.items()
                      if v * factor > 0.01}
        self.relayed_corrupt *= factor
        if self.relayed_corrupt < 0.01:
            self.relayed_corrupt = 0.0
        self.at = now


class VerdictLedger:
    """Daemon-wide typed failure memory, keyed by parent address
    (``ip:download_port`` — peer ids are per-task, addresses are the
    stable identity a byzantine host keeps across tasks)."""

    def __init__(self, *, halflife_s: float = 600.0,
                 shun_threshold: float = SHUN_THRESHOLD,
                 hint_ttl_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic):
        self.halflife_s = halflife_s
        self.shun_threshold = shun_threshold
        self.hint_ttl_s = hint_ttl_s
        self.clock = clock
        self._parents: dict[str, _Parent] = {}
        self.self_quarantined = False
        self.self_reason = ""

    # -- local verdicts (first-hand evidence) --------------------------

    def _get(self, addr: str) -> _Parent:
        p = self._parents.get(addr)
        if p is None:
            p = self._parents[addr] = _Parent()
            p.at = self.clock()
        return p

    def record(self, addr: str, code: str, *, peer_id: str = "",
               relayed: bool = False) -> bool:
        """One locally-verified failure verdict against ``addr``.
        Returns True when this verdict FLIPPED the parent to shunned —
        the caller journals the ``quarantine`` flight event exactly
        once per flip.

        ``relayed`` corruption (the transfer rode the parent's
        cut-through path, X-DF-Relay) is CIRCUMSTANTIAL evidence kept in
        its own decayed counter: the corrupt bytes originated upstream
        of the relay, whose own landing check is about to catch,
        requeue, and stop re-serving them — and however much of it
        accumulates it can only DEPRIORITIZE, never shun. Any lesser
        rule lets one poisoner get every honest relay below it evicted
        (found live by the chaos e2e: at 100% poisoning a busy relay
        racks up relayed verdicts faster than any discount absorbs).
        The true source still earns DIRECT verdicts — from each relay's
        own landing check and from every post-landing disk serve."""
        if not addr or code not in FAIL_CODES:
            return False
        _verdicts.labels(code).inc()
        p = self._get(addr)
        p.decay(self.clock(), self.halflife_s)
        if relayed and code == "corrupt":
            p.relayed_corrupt += 1.0
            if peer_id:
                p.peer_ids.add(peer_id)
            self._export()
            return False
        prev = p.codes.get(code, 0.0)
        p.codes[code] = prev + 1.0
        if peer_id:
            p.peer_ids.add(peer_id)
            if len(p.peer_ids) > 8:
                p.peer_ids.pop()
        # a FLIP is the threshold CROSSING, not a one-shot latch: evidence
        # that decayed below the threshold re-admits the parent, and a
        # re-offense must sever it (and journal) again — a sticky
        # first-flip-only flag would silently disable the response for
        # every relapse after the first decay cycle
        flipped = (code == "corrupt" and prev < self.shun_threshold
                   and p.codes[code] >= self.shun_threshold)
        if flipped:
            log.warning("parent %s shunned: %.1f decayed corrupt "
                        "verdict(s) — locally quarantined", addr,
                        p.codes["corrupt"])
        self._export()
        return flipped

    def record_ok(self, addr: str) -> None:
        if not addr:
            return
        p = self._parents.get(addr)
        if p is not None:
            p.ok += 1

    # -- gossip hints (hearsay: deprioritize ONLY) ---------------------

    # ledger size bound: parents this daemon actually TALKS to are
    # naturally bounded, but hint() ingests attacker-controlled address
    # strings from gossip — without a cap, forged digests with fresh fake
    # addresses every round would grow the ledger (and every snapshot /
    # shunned_addrs walk) without bound
    MAX_PARENTS = 512

    def hint(self, addr: str) -> None:
        """A PEX digest accused ``addr`` of serving corruption. Hearsay:
        refresh the deprioritization window, never the shun counters —
        one byzantine gossiper must not be able to evict an honest host
        (the anti-slander rule, gated by tests/test_quarantine.py)."""
        if not addr:
            return
        _hints.inc()
        if addr not in self._parents \
                and len(self._parents) >= self.MAX_PARENTS:
            # evict the stalest hint-only entry to make room; with none
            # evictable (every entry carries first-hand history), drop
            # the hint — hearsay must never push out real evidence
            victim = min(
                (a for a, p in self._parents.items()
                 if not p.codes and not p.relayed_corrupt and not p.ok),
                key=lambda a: self._parents[a].hinted_at or 0.0,
                default=None)
            if victim is None:
                return
            del self._parents[victim]
        self._get(addr).hinted_at = self.clock()

    # -- queries -------------------------------------------------------

    def corrupt_score(self, addr: str) -> float:
        p = self._parents.get(addr)
        if p is None:
            return 0.0
        p.decay(self.clock(), self.halflife_s)
        return p.codes.get("corrupt", 0.0)

    def shunned(self, addr: str) -> bool:
        """Locally quarantined: enough first-hand corrupt evidence that
        this daemon will not pull from, or index swarm claims of, the
        address — scheduler reachable or not."""
        return self.corrupt_score(addr) >= self.shun_threshold

    def deprioritized(self, addr: str) -> bool:
        """Order behind clean holders (still usable): one local corrupt
        verdict, or a fresh gossip hint."""
        p = self._parents.get(addr)
        if p is None:
            return False
        if p.hinted_at is not None \
                and self.clock() - p.hinted_at <= self.hint_ttl_s:
            return True
        # decay FIRST: a healed relay must work its way back on the same
        # half-life as everything else, not stay deprioritized on a
        # stale counter forever
        p.decay(self.clock(), self.halflife_s)
        if p.relayed_corrupt >= SUSPECT_THRESHOLD:
            return True
        return p.codes.get("corrupt", 0.0) >= SUSPECT_THRESHOLD

    def shunned_addrs(self) -> list[str]:
        return sorted(a for a in self._parents if self.shunned(a))

    # -- self-quarantine -----------------------------------------------

    def self_quarantine(self, reason: str) -> None:
        """This daemon's own storage failed re-verification: it may BE
        the poisoner. Stop advertising (PEX) and flag AnnounceHost —
        the scheduler's registry does the pod-wide half."""
        if not self.self_quarantined:
            log.error("SELF-QUARANTINE: %s — this daemon stops "
                      "advertising and flags its announces", reason)
        self.self_quarantined = True
        self.self_reason = reason
        _selfq_gauge.set(1)

    def _export(self) -> None:
        _shunned_gauge.set(sum(1 for a in self._parents
                               if self.shunned(a)))

    # -- debug surface (GET /debug/verdicts) ---------------------------

    def snapshot(self) -> dict:
        now = self.clock()
        parents = {}
        for addr, p in self._parents.items():
            p.decay(now, self.halflife_s)
            parents[addr] = {
                "codes": {c: round(v, 3) for c, v in p.codes.items()},
                "relayed_corrupt": round(p.relayed_corrupt, 3),
                "ok": p.ok,
                "peer_ids": sorted(p.peer_ids),
                "shunned": self.shunned(addr),
                "deprioritized": self.deprioritized(addr),
                "hinted": bool(p.hinted_at is not None
                               and now - p.hinted_at <= self.hint_ttl_s),
            }
        return {
            "self_quarantined": self.self_quarantined,
            "self_reason": self.self_reason,
            "shun_threshold": self.shun_threshold,
            "halflife_s": self.halflife_s,
            "parents": parents,
        }


def add_verdict_routes(router, ledger: VerdictLedger) -> None:
    """``GET /debug/verdicts`` — mounted on the daemon upload server next
    to /debug/flight (read-only, ring-bounded by the parent count a
    daemon actually talks to, so always on: a poisoned pod must be
    diagnosable — ``dfdiag --pod`` sweeps this surface)."""
    from aiohttp import web

    async def verdicts(_r: web.Request) -> web.Response:
        return web.json_response(ledger.snapshot())

    router.add_get("/debug/verdicts", verdicts)
