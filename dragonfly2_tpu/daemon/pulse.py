"""Pulse digest builder — the daemon half of the fleet telemetry plane.

Folds counters the daemon already maintains (flight ring, served rung
tallies, loop-lag watermarks, SLO breaches, verdict/shun counts, QoS
governor state, storage occupancy) into one compact ``PulseDigest`` that
the announcer piggybacks on AnnounceHost/AnnounceContent. No new
connections, no new timers: the pulse rides the keepalive the daemon
already sends, and building it is a handful of attribute reads — never
a journal replay or an HTTP sweep.

Counters are since-boot monotonic; the scheduler differentiates and
clamps restart resets (`scheduler/fleetpulse.py`). Every read here is
getattr-defensive: a daemon wired without some subsystem (tests, slim
configs) still pulses whatever it has — a partial pulse beats a crashed
announce loop.
"""

from __future__ import annotations

from ..common import health
from ..idl.messages import PulseDigest


def _slo_breaches(plane) -> int:
    slo = getattr(plane, "slo", None)
    counts = getattr(slo, "_counts", None)
    if not counts:
        return 0
    try:
        return int(sum(counts.values()))
    except Exception:
        return 0


def _corrupt_verdicts(verdicts) -> int:
    parents = getattr(verdicts, "_parents", None)
    if not parents:
        return 0
    total = 0.0
    for p in parents.values():
        codes = getattr(p, "codes", None)
        if codes:
            total += codes.get("corrupt", 0.0)
    return int(total)


def build_pulse(daemon, seq: int) -> PulseDigest:
    """One pulse digest from the daemon's live counters. Pure reads —
    calling this must never perturb the subsystems it observes."""
    plane = health.PLANE
    rec = getattr(daemon, "flight_recorder", None)
    verdicts = getattr(daemon, "verdicts", None)
    qos = getattr(daemon, "qos", None)
    storage = getattr(daemon, "storage_mgr", None)

    flight_tasks = len(getattr(rec, "_tasks", ()) or ())
    rungs = dict(getattr(rec, "rung_tallies", None) or {})

    qos_shed = 0
    shed = (getattr(qos, "counters", None) or {}).get("shed")
    if shed:
        try:
            qos_shed = int(sum(shed.values()))
        except Exception:
            qos_shed = 0

    storage_tasks = 0
    if storage is not None:
        try:
            storage_tasks = len(storage.tasks())
        except Exception:
            storage_tasks = 0

    shunned = getattr(verdicts, "shunned_addrs", None)
    return PulseDigest(
        seq=seq,
        flight_tasks=flight_tasks,
        flight_evicted=int(getattr(rec, "evicted", 0) or 0),
        served_rungs=rungs or None,
        loop_lag_max_ms=float(getattr(plane, "max_lag_s", 0.0)) * 1000.0,
        loop_stalls=int(getattr(plane, "stalls", 0)),
        slo_breaches=_slo_breaches(plane),
        corrupt_verdicts=_corrupt_verdicts(verdicts),
        shunned_parents=len(shunned()) if callable(shunned) else 0,
        self_quarantined=bool(getattr(verdicts, "self_quarantined", False)),
        qos_state=str(getattr(qos, "state", "normal") or "normal"),
        qos_shed=qos_shed,
        storage_tasks=storage_tasks,
    )
