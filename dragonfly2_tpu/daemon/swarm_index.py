"""SwarmIndex: the daemon's TTL'd local view of who holds which pieces.

Role parity: none in the reference — Dragonfly2 keeps all piece-location
knowledge in the scheduler. Here the PEX gossip plane (daemon/pex.py)
replicates a *decaying* summary of that knowledge onto every daemon, so a
task can still find mesh parents when every scheduler is unreachable (the
`pex` rung of the degradation ladder, docs/RESILIENCE.md).

Contents: per task, one entry per remote host — address triple (ip,
rpc_port, download_port), ICI coordinates, and the piece set the host
advertised (``None`` = "has every piece", the compact form for completed
tasks, which dominate gossip traffic). Entries expire ``ttl_s`` after the
last digest that named them: a host that stops gossiping stops being
offered as a parent, so the index never accumulates ghosts. The engine's
normal fail/eject ladder handles hosts that lie or die mid-pull.

Everything here is synchronous dict work on the event loop — the gossip
cadence (seconds) and size caps keep it far off the piece hot path.
"""

from __future__ import annotations

import time

from ..common.metrics import REGISTRY
from ..idl.messages import TopologyInfo
from ..tpu.topology import ici_hops, link_type

_swarm_tasks = REGISTRY.gauge(
    "df_swarm_tasks", "tasks the PEX swarm index currently knows holders for")
_swarm_entries = REGISTRY.gauge(
    "df_swarm_entries", "live (task, holder) entries in the PEX swarm index")


class SwarmEntry:
    """One remote host's advertised availability for one task."""

    __slots__ = ("host_id", "ip", "rpc_port", "download_port", "is_seed",
                 "topology", "pieces", "relay_pieces", "total_pieces",
                 "content_length", "piece_size", "done", "expires_at",
                 "progress_at")

    def __init__(self, *, host_id: str, ip: str, rpc_port: int,
                 download_port: int, is_seed: bool = False,
                 topology: TopologyInfo | None = None,
                 pieces: set[int] | None = None,
                 relay_pieces: set[int] | None = None,
                 total_pieces: int = -1,
                 content_length: int = -1, piece_size: int = 0,
                 done: bool = False, expires_at: float = 0.0):
        self.host_id = host_id
        self.ip = ip
        self.rpc_port = rpc_port
        self.download_port = download_port
        self.is_seed = is_seed
        self.topology = topology
        self.pieces = pieces          # None = complete (all pieces)
        # the advertised landing watermark (daemon/relay.py): pieces
        # IN-FLIGHT at the holder when it gossiped — usable for parent
        # ordering and (while FRESH, see progress_at) for the pex rung's
        # coverage gate; a watermark that stopped advancing is a claim,
        # not a holding
        self.relay_pieces = relay_pieces
        self.total_pieces = total_pieces
        self.content_length = content_length
        self.piece_size = piece_size
        self.done = done
        self.expires_at = expires_at
        # when this holder's advertised piece/watermark set last GREW
        # (maintained by SwarmIndex.update): the freshness the coverage
        # gate checks before trusting relay_pieces
        self.progress_at = 0.0

    @property
    def addr(self) -> str:
        return f"{self.ip}:{self.download_port}"

    def piece_count(self) -> int:
        if self.pieces is None:
            return self.total_pieces if self.total_pieces >= 0 else 1 << 30
        return len(self.pieces)

    def advertised_count(self) -> int:
        """Landed + in-flight — the growth signal progress_at tracks."""
        return self.piece_count() + len(self.relay_pieces or ())

    def progress_fresh(self, now: float, ttl_s: float) -> bool:
        """True while the holder's watermark advanced within ``ttl_s`` —
        only then may its in-flight claims count as coverage."""
        return self.done or self.pieces is None \
            or now - self.progress_at <= ttl_s

    def describe(self) -> dict:
        return {"host_id": self.host_id, "addr": self.addr,
                "rpc_port": self.rpc_port, "is_seed": self.is_seed,
                "done": self.done, "pieces": self.piece_count(),
                "relay_pieces": len(self.relay_pieces or ()),
                "total_pieces": self.total_pieces,
                "content_length": self.content_length,
                "progress_age_s": round(
                    max(time.monotonic() - self.progress_at, 0.0), 1),
                "expires_in_s": round(max(self.expires_at - time.monotonic(),
                                          0.0), 1)}


class SwarmIndex:
    """task_id -> {host_id -> SwarmEntry}, TTL'd and size-capped."""

    def __init__(self, *, ttl_s: float = 60.0, max_tasks: int = 512,
                 max_holders_per_task: int = 64,
                 progress_ttl_s: float = 15.0):
        self.ttl_s = ttl_s
        self.max_tasks = max_tasks
        self.max_holders_per_task = max_holders_per_task
        # how long a partial holder's watermark may sit still before its
        # in-flight claims stop counting as coverage (pex._covers_task) —
        # a few gossip intervals: one missed round is jitter, three is a
        # download that died
        self.progress_ttl_s = progress_ttl_s
        self._tasks: dict[str, dict[str, SwarmEntry]] = {}

    # -- ingest --------------------------------------------------------

    def update(self, task_id: str, entry: SwarmEntry,
               *, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        entry.expires_at = now + self.ttl_s
        prev = self._tasks.get(task_id, {}).get(entry.host_id)
        if prev is None or entry.piece_count() > prev.piece_count() \
                or entry.advertised_count() > prev.advertised_count() \
                or (entry.done and not prev.done):
            # first sighting, or the watermark moved: the holder is alive
            # AND landing — only growth refreshes progress (re-gossiping
            # the same stuck set forever must not). The LANDED count is
            # checked on its own: in a download's tail each landing
            # converts an in-flight piece to a landed one one-for-one,
            # so the sum stays flat while the holder is demonstrably
            # still making progress
            entry.progress_at = now
        else:
            entry.progress_at = prev.progress_at
        holders = self._tasks.get(task_id)
        if holders is None:
            if len(self._tasks) >= self.max_tasks:
                # drop the task whose best entry dies soonest — the one the
                # index was about to forget anyway
                victim = min(self._tasks,
                             key=lambda t: max(e.expires_at for e in
                                               self._tasks[t].values()))
                del self._tasks[victim]
            holders = self._tasks[task_id] = {}
        holders[entry.host_id] = entry
        if len(holders) > self.max_holders_per_task:
            victim = min(holders, key=lambda h: holders[h].expires_at)
            del holders[victim]
        self._export_gauges()

    def forget_host(self, host_id: str) -> None:
        """Drop every entry a (now unreachable) host advertised."""
        for holders in self._tasks.values():
            holders.pop(host_id, None)
        self._purge_empty()
        self._export_gauges()

    # -- queries -------------------------------------------------------

    def purge(self, *, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        for holders in self._tasks.values():
            for host_id in [h for h, e in holders.items()
                            if e.expires_at <= now]:
                del holders[host_id]
        self._purge_empty()
        self._export_gauges()

    def _purge_empty(self) -> None:
        for task_id in [t for t, h in self._tasks.items() if not h]:
            del self._tasks[task_id]

    def parents_for(self, task_id: str, *,
                    self_topology: TopologyInfo | None = None,
                    exclude_host: str = "",
                    now: float | None = None) -> list[SwarmEntry]:
        """Live holders of ``task_id``, best parents first: completed
        holders before partial ones, then nearest by link class (ICI
        neighbors before DCN before WAN) and chip-mesh hops — the same
        locality order the scheduler's evaluator applies, collapsed to a
        sort key this side of the control-plane outage."""
        now = time.monotonic() if now is None else now
        holders = self._tasks.get(task_id)
        if not holders:
            return []
        live = [e for e in holders.values()
                if e.expires_at > now and e.host_id != exclude_host]

        def key(e: SwarmEntry):
            lt = link_type(self_topology, e.topology)
            hops = (ici_hops(self_topology, e.topology)
                    if self_topology is not None and e.topology is not None
                    else 1 << 16)
            # stale-watermark partials rank behind fresh ones: a holder
            # whose advertised progress stopped moving is likelier to be
            # a dead download than a busy one
            stale = not e.progress_fresh(now, self.progress_ttl_s)
            return (not e.done, stale, int(lt), hops, -e.piece_count(),
                    e.host_id)

        return sorted(live, key=key)

    def tasks(self) -> list[str]:
        return list(self._tasks)

    def snapshot(self) -> dict:
        return {
            "ttl_s": self.ttl_s,
            "tasks": {tid: [e.describe() for e in holders.values()]
                      for tid, holders in self._tasks.items()},
        }

    def _export_gauges(self) -> None:
        _swarm_tasks.set(len(self._tasks))
        _swarm_entries.set(sum(len(h) for h in self._tasks.values()))
