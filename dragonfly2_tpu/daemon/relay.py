"""Cut-through relay plane: serve a piece while it is still arriving.

Role parity: none in the reference — Dragonfly2 is strictly
store-and-forward at piece granularity: a piece must FULLY land on a
parent before any child may fetch it, so a 1-seed -> N-pod cold start is
serial in tree depth and the seed's uplink sets the pace (the
feeder-limited regime in PAPERS.md "Scale MLPerf-0.6 models on Google
TPU-v3 Pods"). This module is the daemon-side state that removes the
store barrier:

* every in-flight downloaded span (P2P ``piece_engine`` pull or
  back-source ``piece_manager`` stream) registers a ``RelaySpan`` — the
  pooled buffer the bytes are landing in plus a **watermark** of how
  many bytes have arrived. The watermark is advanced by the downloader's
  chunk loop (one integer store per chunk — nothing is copied to
  maintain it) and read by the upload server's streaming range path,
  which serves bytes up to the watermark and awaits the rest with a
  bounded deadline instead of 404ing on an incomplete piece
  (upload_server._serve_relay);
* landed progress is visible through ``TaskStorage.covered_prefix`` —
  the hub combines both so a reader sees one contiguous frontier:
  verified bytes on disk first, then the live span's watermark;
* progress waiters are plain futures resolved by ``pulse()`` — never a
  cross-task ``Condition.wait`` (the 3.10 cancellation hazard documented
  in piece_dispatcher._notified);
* ``inflight_infos`` exposes the spans' piece metadata so the rpcserver
  can announce pieces that are *about to* exist (the control-plane half
  of cut-through: a child may begin pulling from a partial holder), and
  the PEX digest advertises the same watermark pieces with a freshness
  TTL (swarm_index progress_at) so a stalled relay never counts as
  coverage.

Safety: the buffer belongs to the downloader (bufpool contract). A span
is retired — atomically on the event loop, BEFORE the buffer returns to
the pool — once its pieces have landed (or failed verification). Readers
copy with plain ``bytes(buf[lo:hi])`` slices (no lingering memoryview
exports, which would make the pool discard the buffer) and re-check
``retired`` before every copy; after retirement the same bytes are
either on disk (landed, served from storage) or gone (corrupt — the
waiting reader times out and the child requeues the piece against
another holder, exactly the PR 5 corrupt-piece path).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Iterable

from ..common.metrics import REGISTRY
from ..idl.messages import PieceInfo

log = logging.getLogger("df.flow.relay")

_relay_spans = REGISTRY.gauge(
    "df_relay_open_spans", "in-flight downloaded spans readable by the "
    "cut-through relay path")
_relay_tasks = REGISTRY.gauge(
    "df_relay_tasks", "tasks currently tracked by the relay hub "
    "(receiving, relay-servable)")
_relay_pulses = REGISTRY.counter(
    "df_relay_progress_pulses_total",
    "landing-progress pulses delivered to relay waiters")


class RelaySpan:
    """One in-flight downloaded span: the landing buffer + a watermark of
    bytes received so far. ``advance`` is the downloader's per-chunk hot
    path — one attribute store and a (cheap, often waiter-less) pulse."""

    __slots__ = ("task_id", "base", "size", "buf", "pieces", "watermark",
                 "retired", "_hub")

    def __init__(self, hub: "RelayHub", task_id: str, base: int, size: int,
                 buf, pieces: list[PieceInfo]):
        self._hub = hub
        self.task_id = task_id
        self.base = base              # absolute content offset of buf[0]
        self.size = size
        self.buf = buf                # pooled bytearray (downloader-owned)
        self.pieces = pieces          # PieceInfo list (digests may be "")
        self.watermark = 0            # bytes of buf valid so far
        self.retired = False

    def advance(self, watermark: int) -> None:
        if watermark > self.watermark:
            self.watermark = watermark
            self._hub.pulse(self.task_id)

    def end(self) -> int:
        return self.base + self.watermark

    def close(self) -> None:
        self._hub.retire(self)

    def read(self, pos: int, limit: int) -> bytes | None:
        """Copy up to ``limit`` bytes at absolute offset ``pos`` from the
        live buffer; None when this span (no longer) covers ``pos``."""
        if self.retired or pos < self.base or pos >= self.end():
            return None
        lo = pos - self.base
        hi = min(lo + limit, self.watermark)
        # plain slice copy — a memoryview export here would survive into
        # POOL.release's probe and discard the buffer from the pool
        return bytes(self.buf[lo:hi])


class _TaskRelay:
    __slots__ = ("spans", "waiters", "refs", "total_pieces", "on_open")

    def __init__(self):
        self.spans: list[RelaySpan] = []
        self.waiters: list[asyncio.Future] = []
        self.refs = 0                 # conductors landing this task
        self.total_pieces = -1
        self.on_open = None           # announce-ahead hook (conductor)


class RelayHub:
    """Daemon-wide registry: task_id -> in-flight landing state. All
    methods are synchronous event-loop dict work except ``wait_progress``;
    the per-chunk cost on the download hot path is one attribute store."""

    def __init__(self):
        self._tasks: dict[str, _TaskRelay] = {}

    # -- lifecycle (conductor) -----------------------------------------

    def track(self, task_id: str, *, total_pieces: int = -1,
              on_open=None) -> None:
        tr = self._tasks.get(task_id)
        if tr is None:
            tr = self._tasks[task_id] = _TaskRelay()
            _relay_tasks.set(len(self._tasks))
        tr.refs += 1
        if total_pieces >= 0:
            tr.total_pieces = total_pieces
        if on_open is not None:
            tr.on_open = on_open

    def untrack(self, task_id: str) -> None:
        """Conductor finished (success OR fail): wake every waiter so a
        streaming serve parked on this task re-checks and winds down
        instead of riding out its full stall deadline."""
        tr = self._tasks.get(task_id)
        if tr is None:
            return
        tr.refs -= 1
        if tr.refs > 0:
            return
        del self._tasks[task_id]
        _relay_tasks.set(len(self._tasks))
        for span in tr.spans:
            span.retired = True
        self._wake(tr)
        _relay_spans.set(self._span_count())

    def active(self, task_id: str) -> bool:
        return task_id in self._tasks

    # -- spans (downloader / engine / piece manager) -------------------

    def open_span(self, task_id: str, base: int, size: int, buf,
                  pieces: Iterable[PieceInfo]) -> RelaySpan | None:
        tr = self._tasks.get(task_id)
        if tr is None:
            return None
        span = RelaySpan(self, task_id, base, size, buf, list(pieces))
        tr.spans.append(span)
        _relay_spans.set(self._span_count())
        if tr.on_open is not None:
            try:
                tr.on_open(span)
            except Exception:  # noqa: BLE001 - announce is best-effort
                log.exception("relay on_open hook failed")
        return span

    def retire(self, span: RelaySpan | None) -> None:
        """Close a span out of the readable set — called AFTER its pieces
        landed in storage (so the frontier never steps backwards) and
        BEFORE the buffer returns to the pool (so no reader can copy from
        recycled memory). Pulses: the landed bytes are now disk-covered
        and a reader waiting past the old watermark may proceed."""
        if span is None or span.retired:
            return
        span.retired = True
        tr = self._tasks.get(span.task_id)
        if tr is not None:
            try:
                tr.spans.remove(span)
            except ValueError:
                pass
            self._wake(tr)
        _relay_spans.set(self._span_count())

    # -- progress ------------------------------------------------------

    def pulse(self, task_id: str) -> None:
        tr = self._tasks.get(task_id)
        if tr is not None and tr.waiters:
            self._wake(tr)

    def _wake(self, tr: _TaskRelay) -> None:
        if not tr.waiters:
            return
        waiters, tr.waiters = tr.waiters, []
        woken = 0
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)
                woken += 1
        if woken:
            _relay_pulses.inc(woken)

    async def wait_progress(self, task_id: str, timeout_s: float) -> bool:
        """Park until the task's landing frontier moves (watermark advance,
        piece landed, span retired, task finished). False on timeout or
        when the task is not tracked (nothing will ever pulse)."""
        tr = self._tasks.get(task_id)
        if tr is None:
            return False
        fut = asyncio.get_running_loop().create_future()
        tr.waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout_s)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            if not fut.done():
                fut.cancel()

    # -- readers (upload server) ---------------------------------------

    def available_end(self, task_id: str, storage, pos: int,
                      end: int) -> int:
        """The contiguous frontier from ``pos``: how far a reader can go
        right now, combining verified-on-disk pieces and live span
        watermarks (they interleave: a span lands, the next one opens)."""
        cur = pos
        spans = ()
        tr = self._tasks.get(task_id)
        if tr is not None:
            spans = tr.spans
        covered = getattr(storage, "covered_prefix", None)
        while cur < end:
            nxt = cur
            if covered is not None:
                nxt = max(nxt, covered(cur, end))
            for span in spans:
                if not span.retired and span.base <= cur < span.end():
                    nxt = max(nxt, min(span.end(), end))
            if nxt == cur:
                break
            cur = nxt
        return cur

    def read_span(self, task_id: str, pos: int, limit: int) -> bytes | None:
        """Bytes at ``pos`` from a live span (the not-yet-on-disk part of
        the frontier); None when only storage covers it."""
        tr = self._tasks.get(task_id)
        if tr is None:
            return None
        for span in tr.spans:
            out = span.read(pos, limit)
            if out:
                return out
        return None

    def inflight_infos(self, task_id: str) -> list[PieceInfo]:
        """Piece metadata of every live span — the announce-ahead signal:
        these pieces are arriving NOW and a child may begin pulling them
        (the streaming range path serves to the watermark). Digests ride
        along when the span knows them (P2P pulls do; back-source spans
        may not — the child then lands with a computed digest, the same
        trust it gets fetching the origin itself)."""
        tr = self._tasks.get(task_id)
        if tr is None:
            return []
        out: list[PieceInfo] = []
        for span in tr.spans:
            if not span.retired:
                out.extend(span.pieces)
        return out

    def progress(self, task_id: str, storage) -> tuple[int, int]:
        """(landed_pieces, total_pieces) — the advertised watermark for
        the ``X-DF-Piece-Progress`` header and PEX digests."""
        landed = len(getattr(storage.md, "pieces", ()) or ())
        tr = self._tasks.get(task_id)
        total = getattr(storage.md, "total_piece_count", -1)
        if total < 0 and tr is not None:
            total = tr.total_pieces
        return landed, total

    # -- debug ---------------------------------------------------------

    def _span_count(self) -> int:
        return sum(len(tr.spans) for tr in self._tasks.values())

    def snapshot(self) -> dict:
        return {
            "tasks": {
                tid: {
                    "refs": tr.refs,
                    "waiters": len(tr.waiters),
                    "spans": [{"base": s.base, "size": s.size,
                               "watermark": s.watermark,
                               "pieces": [p.piece_num for p in s.pieces]}
                              for s in tr.spans],
                }
                for tid, tr in self._tasks.items()
            },
            "ts": time.time(),
        }
