"""Traffic shaper: split the daemon's total download budget across tasks.

Role parity: reference ``client/daemon/peer/traffic_shaper.go`` — types
``plain`` (equal split) and ``sampling`` (shares proportional to each
task's observed consumption, re-sampled on an interval). Tasks get their
own TokenBucket whose rate the shaper retunes; the engine and back-source
path acquire from it per piece.

Multi-tenant QoS (PR 11): the split is hierarchical. The total budget is
first divided across the PRIORITY_CLASSES service classes by weight over
the classes with live demand (``common/rate.class_shares`` — a lone
``bulk`` herd gets the whole pipe, and loses most of it the moment a
``critical`` task registers), then within each class across its tasks by
the original plain/sampling rule. A ``bulk`` tenant can therefore never
starve ``critical`` traffic of more than its weighted share of the NIC,
no matter how many tasks it floods in.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..common.metrics import REGISTRY
from ..common.rate import TokenBucket, class_shares
from ..idl.messages import DEFAULT_PRIORITY_CLASS, PRIORITY_CLASSES

log = logging.getLogger("df.flow.shaper")

SAMPLE_INTERVAL_S = 1.0
MIN_SHARE_RATIO = 0.05     # no running task starves below 5% of its class

# class weights for the hierarchical split: under full contention
# ``critical`` holds ~73% of the pipe, ``bulk`` degrades to ~9% — the
# graceful-brownout ratio the contended dfbench scenario measures
CLASS_WEIGHTS = {"critical": 8.0, "standard": 3.0, "bulk": 1.0}

_shaper_rate = REGISTRY.gauge(
    "df_shaper_rate_bps", "total download budget the shaper splits "
    "(0 = unlimited, shaper idle)")
_shaper_tasks = REGISTRY.gauge(
    "df_shaper_tasks", "tasks currently registered with the shaper")
_shaper_bytes = REGISTRY.counter(
    "df_shaper_throttled_bytes_total",
    "bytes recorded through shaper-governed tasks")
_shaper_retunes = REGISTRY.counter(
    "df_shaper_retunes_total", "per-task rate redistributions applied")
_qos_class_rate = REGISTRY.gauge(
    "df_qos_class_rate_bps",
    "download budget currently granted to each QoS class by the "
    "hierarchical shaper split (0 while the class is idle or the shaper "
    "is unlimited)", ("cls",))


class _TaskEntry:
    __slots__ = ("bucket", "consumed", "last_consumed", "rate", "cls",
                 "tenant")

    def __init__(self, cls: str = DEFAULT_PRIORITY_CLASS,
                 tenant: str = "") -> None:
        self.bucket = TokenBucket(0)     # unlimited until first retune
        self.consumed = 0
        self.last_consumed = 0
        self.rate = 0.0
        self.cls = cls
        self.tenant = tenant


class TrafficShaper:
    def __init__(self, *, total_rate_bps: float = 0.0,
                 kind: str = "sampling"):
        self.total_rate_bps = float(total_rate_bps)
        self.kind = kind
        self._tasks: dict[str, _TaskEntry] = {}
        self._loop_task: asyncio.Task | None = None

    def start(self) -> None:
        if self.total_rate_bps > 0 and self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._retune_loop())

    async def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass

    # ------------------------------------------------------------------

    def register(self, task_id: str, *,
                 qos_class: str = DEFAULT_PRIORITY_CLASS,
                 tenant: str = "") -> TokenBucket:
        entry = self._tasks.get(task_id)
        if entry is None:
            entry = _TaskEntry(
                qos_class if qos_class in PRIORITY_CLASSES
                else DEFAULT_PRIORITY_CLASS, tenant)
            self._tasks[task_id] = entry
            _shaper_tasks.set(len(self._tasks))
            self._retune()
        return entry.bucket

    def unregister(self, task_id: str) -> None:
        if self._tasks.pop(task_id, None) is not None:
            _shaper_tasks.set(len(self._tasks))
            self._retune()

    def record(self, task_id: str, nbytes: int) -> None:
        entry = self._tasks.get(task_id)
        if entry is not None:
            entry.consumed += nbytes
            if self.total_rate_bps > 0:
                # only governed traffic counts as throttled: with no
                # budget the shaper is a pass-through and the byte is
                # already counted by the transfer-path metrics
                _shaper_bytes.inc(nbytes)

    def class_snapshot(self) -> dict:
        """Per-class registration/consumption/rate readout for
        GET /debug/qos and dfdiag --qos (pure observation)."""
        out: dict[str, dict] = {
            c: {"tasks": 0, "rate_bps": 0.0, "consumed_bytes": 0,
                "tenants": {}} for c in PRIORITY_CLASSES}
        for entry in self._tasks.values():
            row = out[entry.cls]
            row["tasks"] += 1
            row["rate_bps"] += entry.rate
            row["consumed_bytes"] += entry.consumed
            if entry.tenant:
                t = row["tenants"].setdefault(
                    entry.tenant, {"tasks": 0, "consumed_bytes": 0})
                t["tasks"] += 1
                t["consumed_bytes"] += entry.consumed
        return out

    # ------------------------------------------------------------------

    async def _retune_loop(self) -> None:
        while True:
            await asyncio.sleep(SAMPLE_INTERVAL_S)
            self._retune()

    def _retune(self) -> None:
        _shaper_rate.set(self.total_rate_bps)
        if self.total_rate_bps <= 0 or not self._tasks:
            return
        _shaper_retunes.inc()
        # level 1: class shares over live demand. Demand = bytes consumed
        # since the last retune, floored at 1 for any class with a
        # registered task (a just-registered task has consumed nothing
        # yet but must not be scored idle — it would start at the
        # trickle rate and ramp one retune late)
        deltas: dict[str, int] = {}
        class_demand: dict[str, float] = {}
        for tid, entry in self._tasks.items():
            d = max(0, entry.consumed - entry.last_consumed)
            entry.last_consumed = entry.consumed
            deltas[tid] = d
            class_demand[entry.cls] = class_demand.get(entry.cls, 0.0) \
                + max(d, 1)
        shares = class_shares(self.total_rate_bps, CLASS_WEIGHTS,
                              class_demand)
        for cls in PRIORITY_CLASSES:
            _qos_class_rate.labels(cls).set(shares.get(cls, 0.0))
        # level 2: the original plain/sampling rule, within each class
        for cls, budget in shares.items():
            members = {tid: e for tid, e in self._tasks.items()
                       if e.cls == cls}
            if not members or budget <= 0:
                continue
            n = len(members)
            if self.kind == "plain":
                share = budget / n
                for entry in members.values():
                    entry.rate = share
                    entry.bucket.set_rate(share)
                continue
            total_delta = sum(deltas[tid] for tid in members)
            floor = budget * MIN_SHARE_RATIO
            distributable = budget - floor * n
            if distributable <= 0 or total_delta == 0:
                share = budget / n
                for entry in members.values():
                    entry.rate = share
                    entry.bucket.set_rate(share)
                continue
            for tid, entry in members.items():
                entry.rate = floor + distributable * deltas[tid] / total_delta
                entry.bucket.set_rate(entry.rate)
