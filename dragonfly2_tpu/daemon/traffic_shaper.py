"""Traffic shaper: split the daemon's total download budget across tasks.

Role parity: reference ``client/daemon/peer/traffic_shaper.go`` — types
``plain`` (equal split) and ``sampling`` (shares proportional to each
task's observed consumption, re-sampled on an interval). Tasks get their
own TokenBucket whose rate the shaper retunes; the engine and back-source
path acquire from it per piece.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..common.metrics import REGISTRY
from ..common.rate import TokenBucket

log = logging.getLogger("df.flow.shaper")

SAMPLE_INTERVAL_S = 1.0
MIN_SHARE_RATIO = 0.05     # no running task starves below 5% of total

_shaper_rate = REGISTRY.gauge(
    "df_shaper_rate_bps", "total download budget the shaper splits "
    "(0 = unlimited, shaper idle)")
_shaper_tasks = REGISTRY.gauge(
    "df_shaper_tasks", "tasks currently registered with the shaper")
_shaper_bytes = REGISTRY.counter(
    "df_shaper_throttled_bytes_total",
    "bytes recorded through shaper-governed tasks")
_shaper_retunes = REGISTRY.counter(
    "df_shaper_retunes_total", "per-task rate redistributions applied")


class _TaskEntry:
    __slots__ = ("bucket", "consumed", "last_consumed", "rate")

    def __init__(self) -> None:
        self.bucket = TokenBucket(0)     # unlimited until first retune
        self.consumed = 0
        self.last_consumed = 0
        self.rate = 0.0


class TrafficShaper:
    def __init__(self, *, total_rate_bps: float = 0.0,
                 kind: str = "sampling"):
        self.total_rate_bps = float(total_rate_bps)
        self.kind = kind
        self._tasks: dict[str, _TaskEntry] = {}
        self._loop_task: asyncio.Task | None = None

    def start(self) -> None:
        if self.total_rate_bps > 0 and self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._retune_loop())

    async def stop(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass

    # ------------------------------------------------------------------

    def register(self, task_id: str) -> TokenBucket:
        entry = self._tasks.get(task_id)
        if entry is None:
            entry = _TaskEntry()
            self._tasks[task_id] = entry
            _shaper_tasks.set(len(self._tasks))
            self._retune()
        return entry.bucket

    def unregister(self, task_id: str) -> None:
        if self._tasks.pop(task_id, None) is not None:
            _shaper_tasks.set(len(self._tasks))
            self._retune()

    def record(self, task_id: str, nbytes: int) -> None:
        entry = self._tasks.get(task_id)
        if entry is not None:
            entry.consumed += nbytes
            if self.total_rate_bps > 0:
                # only governed traffic counts as throttled: with no
                # budget the shaper is a pass-through and the byte is
                # already counted by the transfer-path metrics
                _shaper_bytes.inc(nbytes)

    # ------------------------------------------------------------------

    async def _retune_loop(self) -> None:
        while True:
            await asyncio.sleep(SAMPLE_INTERVAL_S)
            self._retune()

    def _retune(self) -> None:
        _shaper_rate.set(self.total_rate_bps)
        if self.total_rate_bps <= 0 or not self._tasks:
            return
        _shaper_retunes.inc()
        n = len(self._tasks)
        if self.kind == "plain":
            share = self.total_rate_bps / n
            for entry in self._tasks.values():
                entry.rate = share
                entry.bucket.set_rate(share)
            return
        # sampling: weight by bytes consumed since the last retune, with a
        # floor so idle-but-running tasks can ramp back up
        deltas = {}
        total_delta = 0
        for tid, entry in self._tasks.items():
            d = max(0, entry.consumed - entry.last_consumed)
            entry.last_consumed = entry.consumed
            deltas[tid] = d
            total_delta += d
        floor = self.total_rate_bps * MIN_SHARE_RATIO
        distributable = self.total_rate_bps - floor * n
        if distributable <= 0 or total_delta == 0:
            share = self.total_rate_bps / n
            for entry in self._tasks.values():
                entry.rate = share
                entry.bucket.set_rate(share)
            return
        for tid, entry in self._tasks.items():
            entry.rate = floor + distributable * deltas[tid] / total_delta
            entry.bucket.set_rate(entry.rate)
