"""Download flight recorder: a ring-buffered per-task event journal.

Role parity: none in the reference — this is the TPU-native observability
plane PAPER §1 calls for. Scheduling quality depends on knowing, per piece,
where time went: queueing on the parent, the wire transfer, or the HBM
device transfer. The recorder captures every piece's lifecycle

    scheduled -> dispatched -> first_byte -> wire_done -> hbm_done

with parent peer id, source (p2p vs back-to-source), and byte counts, and
can summarize a finished task (slowest-piece attribution, per-parent
throughput, tail-latency breakdown, back-to-source ratio).

Overhead contract (bench-critical — every piece of a v5p fan-out crosses
this path):
  * recording one event is a single ``deque.append`` of a tuple — O(1),
    no allocation beyond the tuple, no locks (asyncio single-threaded);
  * per-task event count is ring-capped (``max_events``, drop-oldest);
  * the recorder keeps at most ``max_tasks`` flights (drop-oldest);
  * while disabled, ``begin()`` returns None and callers hold a None —
    the hot path then never even enters this module.

Exposure: ``GET /debug/flight`` (+ ``/<task_id>``) on the daemon upload
server (upload_server.py), a compact summary attached to the terminal
``PeerResult`` (scheduler_session.py) feeding the scheduler's cluster view
and the trainer's record stream, and the ``dfdiag`` CLI waterfall.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque

from ..common.metrics import REGISTRY

# flight-ring visibility: operators must be able to tell when max_tasks
# is silently dropping history under churn (the index carries occupancy
# and this counter carries the drops)
_flight_evicted = REGISTRY.counter(
    "df_flight_evicted_total",
    "flights dropped from the recorder ring to admit newer tasks")
_flight_tasks = REGISTRY.gauge(
    "df_flight_tasks", "flights currently held in the recorder ring")
_serve_rows = REGISTRY.counter(
    "df_flight_serve_rows_total",
    "serve-side edge rows journaled by the upload server")

# piece lifecycle stages (strings, interned by the parser — kept short
# because every event tuple carries one)
SCHEDULED = "scheduled"      # dispatcher handed the piece to a worker
DISPATCHED = "dispatched"    # HTTP GET to the parent is about to fire
FIRST_BYTE = "first_byte"    # first body chunk arrived (per request)
WIRE_DONE = "wire_done"      # piece bytes fully on the wire, verified
HBM_DONE = "hbm_done"        # piece staged for the device sink
CORRUPT = "corrupt"          # digest mismatch at landing (parent = sender):
# the piece was requeued; repeated corrupt events from one parent are the
# dfdiag fingerprint of a corrupting peer (bad NIC/disk), and the summary
# counts them per parent so the verdict can name it
# typed transfer-failure kinds (idl.FAIL_CODES minus corrupt, which has
# its own richer event above): one event per failed fetch, parent = the
# failing sender — the summary folds all four into ``fail_codes`` so
# dfdiag and the ledger joins can learn from failure *kind*, not just a
# bare ok=False
STALL = "stall"              # transfer died mid-body (short read/reset)
TIMEOUT = "timeout"          # per-piece deadline fired
REFUSED = "refused"          # parent errored before any payload moved
QUARANTINE = "quarantine"    # the verdict ledger flipped a parent to
# locally shunned DURING this task (parent = the shunned address): the
# journal shows exactly when the immune response engaged, next to the
# corrupt events that triggered it
PLACED = "placed"            # dedupe hit (parent = "cas"): the piece's
# bytes were already on disk under another task's digest and were placed
# locally by the content store — zero wire bytes moved; the summary
# carries these as bytes_placed so podscope can tell a warm pod (origin
# bytes 0 because nothing needed transferring) from a blind one
SHARD_READY = "shard_ready"  # a named manifest shard's bytes all verified
# (parent = shard name, bytes = shard size, piece = source class index
# into SHARD_SRC_NAMES): the moment the shard became eligible to be a
# ready device array — the sharded-task analog of wire_done, and the
# series dfget's per-shard timestamps and the pr14 bench makespan read
SHARD_FALLBACK = "shard_fallback"  # a swap-class piece (a shard assigned
# to a co-located replica's tree fetch) ran out its swap hold and was
# re-pulled from the tree instead (parent = the serving parent): the
# ICI-swap partner died or stalled, and the bounded hold kept the task
# from wedging on it — the sharded analog of a degradation-ladder rung
# task-level stages
REGISTERED = "registered"    # scheduler register returned
HBM_SHARD = "hbm_shard"      # one device DMA completed (piece = shard idx)
DONE = "done"                # task reached a terminal state
RUNG = "rung"                # degradation-ladder transition (parent = rung)
QOS = "qos"                  # QoS admission ruling (parent = governor
# state the task was admitted under: a bulk task that rode the brownout
# queue carries a qos/brownout event, so "why did this pull start late"
# is answerable from the journal — the admission-side analog of a rung)
UPLOAD = "upload"            # serve-side edge row (TaskFlight.serve ring):
# a piece/range THIS daemon served to a child, journaled by the upload
# server so every transfer edge is observed from both ends — podscope
# stitches these against the child's download rows even on the
# scheduler-less pex rung, where no scheduler ever saw the edge

# the conductor's six-rung degradation ladder (docs/RESILIENCE.md): the
# rung event's parent field names which rung the task just entered, so
# dfdiag can show which rung ultimately served a slow task
RUNG_P2P = "p2p"                      # scheduler gave parents; mesh pull
RUNG_RESCHEDULE = "reschedule"        # parents died; waiting re-assignment
RUNG_RING_FAILOVER = "ring_failover"  # hashed scheduler dead; next member
RUNG_PEX = "pex"                      # schedulers gone; gossip-found parents
RUNG_BACK_SOURCE = "back_source"      # fetching from origin
RUNG_FAIL = "fail"                    # ladder exhausted; coded verdict

ORIGIN = ""                  # parent id of a back-to-source fetch

# SHARD_READY source classes, indexed by the event's piece field: which
# path supplied the shard's bytes — the host's own assigned tree fetch,
# or co-located replicas over ICI-near P2P (the shard swap)
SHARD_SRC_NAMES = ("tree", "swap")
SHARD_SRC_TREE, SHARD_SRC_SWAP = 0, 1


class TaskFlight:
    """One task's event journal. Events are ``(t_ms, stage, piece, parent,
    bytes, dur_ms)`` tuples relative to the flight's start."""

    __slots__ = ("task_id", "peer_id", "started_at", "_m0", "events",
                 "serves", "state", "url", "report_drops", "_sum_key",
                 "_sum_cache", "qos_class", "tenant", "shards_total",
                 "on_rung")

    def __init__(self, task_id: str, peer_id: str, *, url: str = "",
                 max_events: int = 4096, max_serves: int = 1024,
                 qos_class: str = "", tenant: str = ""):
        self.task_id = task_id
        self.peer_id = peer_id
        self.url = url
        # QoS attribution: the class rides the summary so the SLO engine
        # can judge this flight against ITS class's budgets and podscope
        # can attribute contention to the tenant that caused it
        self.qos_class = qos_class
        self.tenant = tenant
        self.started_at = time.time()
        self._m0 = time.monotonic()
        self.events: deque = deque(maxlen=max_events)
        # serve-side edge journal (UPLOAD rows): (t_ms, peer, addr, piece,
        # bytes, serve_ms, wait_ms) per range served to a child. A separate
        # ring so a hot seed's thousands of serves can never evict its own
        # download journal, and so the piece-row stage math stays blind to
        # them.
        self.serves: deque = deque(maxlen=max_serves)
        self.state = "running"
        # piece reports dropped because the scheduler stream's writer died
        # (scheduler_session.report_piece) — a silent drop becomes a ghost
        # peer on the scheduler, so the count rides the flight summary
        self.report_drops = 0
        # sharded tasks: how many manifest shards this download tracks
        # (0 = not sharded) — set by the conductor so the summary's
        # shards block can report ready/total without replaying events
        self.shards_total = 0
        self._sum_key: tuple | None = None   # summarize() memo (see there)
        self._sum_cache: dict = {}
        # daemon-wide rung tally hook (FlightRecorder._note_rung): the
        # fleet pulse needs cumulative served-rung counts without a
        # summarize() replay per announce, so rung() tallies through here
        self.on_rung = None

    # -- recording (hot path) ------------------------------------------

    def now_ms(self) -> float:
        return (time.monotonic() - self._m0) * 1000.0

    def event(self, stage: str, piece: int = -1, parent: str = ORIGIN,
              nbytes: int = 0, dur_ms: float = 0.0,
              t_ms: float | None = None) -> None:
        """``t_ms``: explicit timestamp (from now_ms()) for events whose
        moment precedes their recording — a wire_done journaled only once
        the piece verified and landed."""
        self.events.append(
            (self.now_ms() if t_ms is None else t_ms, stage, piece,
             parent, nbytes, dur_ms))

    def finish(self, state: str) -> None:
        self.state = state
        self.event(DONE)

    def rung(self, name: str) -> None:
        """Journal a degradation-ladder transition (RUNG_* constants)."""
        self.event(RUNG, parent=name)
        if self.on_rung is not None:
            self.on_rung(name)

    def serve(self, *, peer: str, addr: str = "", piece: int = -1,
              nbytes: int = 0, serve_ms: float = 0.0,
              wait_ms: float = 0.0, pieces: int = 1,
              relayed: bool = False) -> None:
        """Journal one range served to a child (the UPLOAD edge row).

        ``peer`` is the requesting child's peer id (the ?peerId= on the
        piece GET) and ``addr`` its socket address; ``serve_ms`` covers
        limiter wait + storage read + body transmit (the upload slot's
        hold time), ``wait_ms`` the limiter share of it. ``piece`` is the
        FIRST piece of the range and ``pieces`` how many it spans — a
        grouped span GET is one row, but the parent-side piece count must
        still agree with the child's per-piece rows. ``relayed`` marks a
        cut-through serve (the range streamed against the landing
        watermark, daemon/relay.py) so podscope can surface relay edges
        and their depth. One deque append — same hot-path overhead
        contract as event()."""
        self.serves.append((self.now_ms(), peer, addr, piece, nbytes,
                            serve_ms, wait_ms, pieces, relayed))
        _serve_rows.inc()

    def hbm_spans(self, spans: list) -> None:
        """Adopt a DeviceIngest's completed transfer spans ((monotonic
        start, end) pairs) as shard-level events on this flight's clock."""
        for idx, (t0, t1) in enumerate(spans):
            self.events.append(((t0 - self._m0) * 1000.0, HBM_SHARD, idx,
                                ORIGIN, 0, (t1 - t0) * 1000.0))

    # -- consumption ---------------------------------------------------

    def timeline(self) -> dict:
        return {
            "task_id": self.task_id, "peer_id": self.peer_id,
            "url": self.url, "started_at": self.started_at,
            "state": self.state,
            "events": [{"t_ms": round(t, 3), "stage": stage, "piece": piece,
                        "parent": parent, "bytes": nbytes,
                        "dur_ms": round(dur, 3)}
                       for t, stage, piece, parent, nbytes, dur in
                       self.events],
            "serves": [{"t_ms": round(t, 3), "stage": UPLOAD, "peer": peer,
                        "addr": addr, "piece": piece, "pieces": pieces,
                        "bytes": nbytes,
                        "serve_ms": round(serve, 3),
                        "wait_ms": round(wait, 3),
                        "relayed": relayed}
                       for t, peer, addr, piece, nbytes, serve, wait,
                       pieces, relayed in self.serves],
        }

    def summarize(self) -> dict:
        """Machine-readable attribution: per-piece stage breakdown,
        per-parent throughput, slowest piece + its dominant stage, tail
        latencies, back-to-source ratio.

        Memoized on (event count, state): a finished task is summarized
        at least twice back-to-back (SLO accounting at conductor finish,
        then the compact PeerResult form), and the O(events) walk need
        not run twice. Returns a shallow copy so consumers may del/replace
        top-level keys (compact_summary does)."""
        # last event rides the key: a ring at maxlen keeps a constant
        # length while events churn, so length alone would serve a stale
        # mid-flight summary from the HTTP surface
        key = (len(self.events), self.state, self.report_drops,
               self.events[-1] if self.events else None,
               len(self.serves), self.serves[-1] if self.serves else None,
               self.shards_total)
        if key == self._sum_key:
            return dict(self._sum_cache)
        pieces: dict[int, dict] = {}
        parents: dict[str, dict] = {}
        rungs: list[str] = []
        corrupt: dict[str, int] = {}
        fail_codes: dict[str, int] = {}
        quarantined: list[str] = []
        hbm_dma_ms = 0.0
        placed_pieces = 0
        bytes_placed = 0
        shard_rows: list[dict] = []
        shard_fallbacks = 0
        for t, stage, piece, parent, nbytes, dur in self.events:
            if stage == HBM_SHARD:
                hbm_dma_ms += dur
                continue
            if stage == SHARD_READY:
                src = (SHARD_SRC_NAMES[piece]
                       if 0 <= piece < len(SHARD_SRC_NAMES) else "tree")
                shard_rows.append({"name": parent, "src": src,
                                   "t_ms": round(t, 3), "bytes": nbytes})
                continue
            if stage == SHARD_FALLBACK:
                shard_fallbacks += 1
                continue
            if stage == PLACED:
                # content-store placements moved zero wire bytes: counted
                # apart from p2p/source so origin accounting stays honest
                placed_pieces += 1
                bytes_placed += nbytes
                continue
            if stage == CORRUPT:
                corrupt[parent] = corrupt.get(parent, 0) + 1
                fail_codes[CORRUPT] = fail_codes.get(CORRUPT, 0) + 1
                continue
            if stage in (STALL, TIMEOUT, REFUSED):
                fail_codes[stage] = fail_codes.get(stage, 0) + 1
                continue
            if stage == QUARANTINE:
                if parent not in quarantined:
                    quarantined.append(parent)
                continue
            if stage == RUNG:
                # dedupe consecutive repeats (reschedule can re-fire while
                # the same outage is still in progress)
                if not rungs or rungs[-1] != parent:
                    rungs.append(parent)
                continue
            if piece < 0:
                continue
            p = pieces.setdefault(piece, {})
            if stage == WIRE_DONE:
                p[WIRE_DONE] = t
                p["bytes"] = nbytes
                p["parent"] = parent
                p["wire_dur"] = dur
            elif stage == HBM_DONE:
                p[HBM_DONE] = t
            else:
                # pre-wire stages keyed by parent: endgame racers journal
                # their own attempts, and only the entries of the parent
                # that actually delivered (the WIRE_DONE one) are read at
                # row-build time — a loser can never rewrite the winner's
                # stage history, whichever order their events landed
                p.setdefault(stage, {})[parent] = t
        piece_rows = []
        for num in sorted(pieces):
            p = pieces[num]
            wire_end = p.get(WIRE_DONE)
            if wire_end is None:
                continue
            winner = p.get("parent", ORIGIN)
            # pieces that skipped the dispatcher (back-source) carry their
            # measured duration on the wire_done event: back-date the start
            sched = (p.get(SCHEDULED) or {}).get(winner)
            if sched is None:
                sched = wire_end - p.get("wire_dur", 0.0)
            disp = (p.get(DISPATCHED) or {}).get(winner, sched)
            first = (p.get(FIRST_BYTE) or {}).get(winner)
            if first is None:
                # grouped-span members get no first_byte of their own:
                # back-date from the per-piece duration so wire_ms is this
                # piece's transfer share, not the whole span window
                first = max(disp, wire_end - p.get("wire_dur", 0.0))
            hbm = p.get(HBM_DONE, wire_end)
            stages = {
                "queue_ms": max(disp - sched, 0.0),
                "ttfb_ms": max(first - disp, 0.0),
                "wire_ms": max(wire_end - first, 0.0),
                "hbm_ms": max(hbm - wire_end, 0.0),
            }
            total = wire_end - sched + stages["hbm_ms"]
            parent = winner
            row = {"piece": num, "parent": parent,
                   "source": "origin" if parent == ORIGIN else "p2p",
                   "bytes": p.get("bytes", 0),
                   "start_ms": round(sched, 3),
                   "total_ms": round(total, 3),
                   **{k: round(v, 3) for k, v in stages.items()}}
            piece_rows.append(row)
            # accrued from the DEDUPED piece table, not per event (endgame
            # duplicates must not inflate a parent), and from wire time
            # only — folding ttfb in would divide a span-serving parent's
            # throughput by its group size and flag it as a straggler
            pp = parents.setdefault(
                parent, {"bytes": 0, "pieces": 0, "wire_ms": 0.0})
            pp["bytes"] += row["bytes"]
            pp["pieces"] += 1
            pp["wire_ms"] += stages["wire_ms"]
        for pp in parents.values():
            ms = pp["wire_ms"]
            pp["wire_ms"] = round(ms, 3)
            pp["throughput_bps"] = (
                round(pp["bytes"] / (ms / 1000.0)) if ms > 0 else 0)
        # serve-side edges, aggregated per requesting child: the parent
        # half of every transfer edge (podscope joins this against the
        # child's piece rows to confirm the edge from both ends)
        uploads: dict[str, dict] = {}
        for _t, peer, addr, _piece, nbytes, serve, wait, npieces, \
                relayed in self.serves:
            up = uploads.setdefault(peer or addr, {
                "addr": addr, "bytes": 0, "pieces": 0,
                "serve_ms": 0.0, "wait_ms": 0.0, "relayed_pieces": 0})
            up["bytes"] += nbytes
            up["pieces"] += npieces
            up["serve_ms"] += serve
            up["wait_ms"] += wait
            if relayed:
                up["relayed_pieces"] += npieces
        for up in uploads.values():
            ms = up["serve_ms"]
            up["serve_ms"] = round(ms, 3)
            up["wait_ms"] = round(up["wait_ms"], 3)
            up["serve_bps"] = (round(up["bytes"] / (ms / 1000.0))
                               if ms > 0 else 0)
        totals = sorted(r["total_ms"] for r in piece_rows)
        slowest = max(piece_rows, key=lambda r: r["total_ms"],
                      default=None)
        summary = {
            "task_id": self.task_id, "peer_id": self.peer_id,
            "state": self.state,
            "pieces": len(piece_rows),
            "bytes_p2p": sum(r["bytes"] for r in piece_rows
                             if r["source"] == "p2p"),
            "bytes_source": sum(r["bytes"] for r in piece_rows
                                if r["source"] == "origin"),
            "bytes_placed": bytes_placed,
            "placed_pieces": placed_pieces,
            "per_parent": parents,
            "uploads": uploads,
            "bytes_served": sum(u["bytes"] for u in uploads.values()),
            "tail_ms": {"p50": _pctl(totals, 0.50),
                        "p90": _pctl(totals, 0.90),
                        "p99": _pctl(totals, 0.99)},
            "hbm_dma_ms": round(hbm_dma_ms, 3),
            # the degradation-ladder trail and the rung the task ended on —
            # dfdiag's verdict names it so "why did this go to origin"
            # never needs log spelunking
            "rungs": rungs,
            "served_rung": rungs[-1] if rungs else "",
            # QoS attribution ("" = pre-QoS / classless): the SLO engine
            # scales stage budgets by this class, dfdiag names it
            "qos_class": self.qos_class,
            "tenant": self.tenant,
            "report_drops": self.report_drops,
            # digest-mismatched transfers per sending parent (the piece
            # itself was requeued and its eventual row credits whoever
            # delivered the good copy)
            "corrupt_pieces": corrupt,
            # typed failure tallies (FAIL_CODES) across the whole flight:
            # what KIND of failures this download absorbed — the wasted-
            # work attribution the quarantine plane is judged by
            "fail_codes": fail_codes,
            # parent addresses the local verdict ledger shunned during
            # this task (the `quarantine` events): dfdiag names them
            "quarantined_parents": quarantined,
            "piece_rows": piece_rows,
        }
        if self.shards_total or shard_rows:
            # sharded-task readiness: one row per completed shard (name,
            # tree vs swap, ready timestamp) plus the slowest — what
            # dfdiag's verdict and podscope's per-task shards line read
            shards: dict = {
                "total": self.shards_total or len(shard_rows),
                "ready": len(shard_rows),
                "tree_bytes": sum(r["bytes"] for r in shard_rows
                                  if r["src"] == "tree"),
                "swap_bytes": sum(r["bytes"] for r in shard_rows
                                  if r["src"] == "swap"),
                "fallbacks": shard_fallbacks,
                "rows": shard_rows,
            }
            if shard_rows:
                shards["slowest"] = max(shard_rows,
                                        key=lambda r: r["t_ms"])
            summary["shards"] = shards
        total_bytes = summary["bytes_p2p"] + summary["bytes_source"]
        summary["back_to_source_ratio"] = (
            round(summary["bytes_source"] / total_bytes, 4)
            if total_bytes else 0.0)
        # per-stage SLO budget verdict rides every summary surface (HTTP,
        # dfdiag, the compact PeerResult form) — pure annotation; the
        # breach COUNTERS are incremented once per task by the conductor
        from ..common.health import PLANE
        PLANE.slo.annotate(summary)
        if slowest is not None:
            stage = max(("queue_ms", "ttfb_ms", "wire_ms", "hbm_ms"),
                        key=lambda k: slowest[k])
            summary["slowest_piece"] = {
                "piece": slowest["piece"], "parent": slowest["parent"],
                "total_ms": slowest["total_ms"],
                "dominant_stage": stage.removesuffix("_ms"),
                "dominant_ms": slowest[stage]}
        self._sum_key, self._sum_cache = key, summary
        return dict(summary)

    def compact_summary(self, *, max_parents: int = 8) -> dict:
        """The wire form attached to the terminal PeerResult: the summary
        minus per-piece rows, parents capped to the heaviest few (a
        1000-piece task must not ship a 1000-row report)."""
        s = self.summarize()
        del s["piece_rows"]
        if "shards" in s:
            # same cap rationale as piece_rows: a 1000-shard checkpoint
            # must not ship a 1000-row report — keep the latest-ready few
            # (the tail that sets time-to-serving), totals stay exact
            sh = dict(s["shards"])
            sh["rows"] = sorted(sh["rows"], key=lambda r: r["t_ms"],
                                reverse=True)[:max_parents]
            s["shards"] = sh
        parents = sorted(s["per_parent"].items(),
                         key=lambda kv: kv[1]["bytes"], reverse=True)
        s["per_parent"] = dict(parents[:max_parents])
        uploads = sorted(s["uploads"].items(),
                         key=lambda kv: kv[1]["bytes"], reverse=True)
        s["uploads"] = dict(uploads[:max_parents])
        return s


# one percentile rule repo-wide (canonical impl in common/podscope.py;
# re-exported here because every flight-summary consumer — dfbench, the
# SLO engine, podscope itself — keys on these exact cut points)
from ..common.podscope import _pctl  # noqa: E402


class FlightRecorder:
    """Daemon-wide registry of TaskFlights, ring-capped on task count."""

    def __init__(self, *, enabled: bool = True, max_tasks: int = 64,
                 max_events: int = 4096, max_serves: int = 1024):
        self.enabled = enabled
        self.max_tasks = max_tasks
        self.max_events = max_events
        self.max_serves = max_serves
        # flights dropped to admit newer tasks since boot — surfaced in
        # the /debug/flight index so an operator can tell a quiet pod
        # from one whose history is churning out of the ring
        self.evicted = 0
        # cumulative served-rung tallies since boot (rung name -> count):
        # flights tally through on_rung at transition time so the fleet
        # pulse reads a dict, never replays journals; survives flight
        # eviction (the ring caps history, not the counters)
        self.rung_tallies: dict[str, int] = {}
        self._tasks: OrderedDict[str, TaskFlight] = OrderedDict()

    def _note_rung(self, name: str) -> None:
        self.rung_tallies[name] = self.rung_tallies.get(name, 0) + 1

    def begin(self, task_id: str, peer_id: str, url: str = "",
              qos_class: str = "", tenant: str = "") -> TaskFlight | None:
        """Open (or reopen) a flight; None while disabled so callers hold
        a None and the hot path never calls back in."""
        if not self.enabled:
            return None
        # the upload port is mesh-reachable and the flight surface is not
        # auth-gated: strip the query string (presigned-URL credentials)
        # before the URL becomes queryable debug state
        flight = TaskFlight(task_id, peer_id, url=url.split("?", 1)[0],
                            max_events=self.max_events,
                            max_serves=self.max_serves,
                            qos_class=qos_class, tenant=tenant)
        flight.on_rung = self._note_rung
        self._tasks[task_id] = flight
        self._tasks.move_to_end(task_id)
        while len(self._tasks) > self.max_tasks:
            self._tasks.popitem(last=False)
            self.evicted += 1
            _flight_evicted.inc()
        _flight_tasks.set(len(self._tasks))
        return flight

    def serving(self, task_id: str, peer_id: str = "") -> TaskFlight | None:
        """Get-or-create the flight a serve row lands on. A daemon that
        downloaded the task journals serves onto its download flight (one
        surface per task); a daemon serving content it never downloaded
        here — a restarted seed re-seeded from disk — gets a fresh flight
        in state 'serving' so its edges are still observable.

        Serve traffic must NEVER evict a download flight: a seed holding
        more tasks than ``max_tasks`` would otherwise churn its own
        in-flight download journals out of the ring with every fan-out.
        A serve-only flight is admitted by evicting the oldest OTHER
        serve-only flight; with the ring full of download flights it is
        simply not journaled (the child side still observes the edge)."""
        if not self.enabled:
            return None
        flight = self._tasks.get(task_id)
        if flight is not None:
            return flight            # no move_to_end: serves don't renew
        if len(self._tasks) >= self.max_tasks:
            victim = next((tid for tid, f in self._tasks.items()
                           if f.state == "serving"), None)
            if victim is None:
                return None
            del self._tasks[victim]
            self.evicted += 1
            _flight_evicted.inc()
        flight = TaskFlight(task_id, peer_id,
                            max_events=self.max_events,
                            max_serves=self.max_serves)
        flight.on_rung = self._note_rung
        flight.state = "serving"
        self._tasks[task_id] = flight
        _flight_tasks.set(len(self._tasks))
        return flight

    def get(self, task_id: str) -> TaskFlight | None:
        return self._tasks.get(task_id)

    def index(self) -> list[dict]:
        return [{"task_id": f.task_id, "state": f.state,
                 "started_at": f.started_at, "events": len(f.events),
                 "serves": len(f.serves)}
                for f in self._tasks.values()]


def add_flight_routes(router, recorder: FlightRecorder) -> None:
    """``GET /debug/flight`` (index) and ``/debug/flight/{task_id}``
    (?summary=1 for the attribution summary instead of the raw timeline).
    Mounted on the daemon upload server next to /metrics — read-only and
    cheap, so not gated behind the profiling flag."""
    import json

    from aiohttp import web

    async def flight_index(_r: web.Request) -> web.Response:
        # ring visibility: occupancy vs max_tasks + the eviction count —
        # evicted > 0 with a full ring means history is being dropped
        # under churn and max_tasks needs raising (or dfdiag, run sooner)
        return web.json_response({"enabled": recorder.enabled,
                                  "max_tasks": recorder.max_tasks,
                                  "occupancy": len(recorder._tasks),
                                  "evicted_total": recorder.evicted,
                                  "tasks": recorder.index()})

    async def flight_one(request: web.Request) -> web.Response:
        task_id = request.match_info["task_id"]
        flight = recorder.get(task_id)
        if flight is None:
            # prefix match: operators paste truncated ids from logs
            matches = [f for tid, f in recorder._tasks.items()
                       if tid.startswith(task_id)]
            if len(matches) != 1:
                raise web.HTTPNotFound(
                    text=json.dumps({"error": f"no flight for {task_id}"}),
                    content_type="application/json")
            flight = matches[0]
        if request.query.get("summary"):
            return web.json_response(flight.summarize())
        body = flight.timeline()
        body["summary"] = flight.summarize()
        return web.json_response(body)

    router.add_get("/debug/flight", flight_index)
    router.add_get("/debug/flight/{task_id}", flight_one)
