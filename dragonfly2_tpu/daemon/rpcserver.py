"""Daemon gRPC services.

Role parity: reference ``client/daemon/rpcserver/rpcserver.go`` — the local
API (``Download`` server-stream, cache ops) and the peer API
(``GetPieceTasks``, ``SyncPieceTasks`` bidi, seeder ``ObtainSeeds``).
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator

from ..common.errors import Code, DFError
from ..idl.messages import (DeleteTaskRequest, DownloadRequest, Empty,
                            ExportTaskRequest, ImportTaskRequest,
                            ObtainSeedsRequest, PiecePacket, PieceSeed,
                            PieceTaskRequest, StatTaskDaemonRequest, TaskStat,
                            UrlMeta)
from ..rpc.server import RPCServer, ServiceDef
from .peertask_manager import PeerTaskManager

log = logging.getLogger("df.rpc.daemon")

DAEMON_SERVICE = "df.daemon.Daemon"
SEEDER_SERVICE = "df.daemon.Seeder"


class DaemonService:
    """Wire handlers; pure delegation to PeerTaskManager + storage."""

    def __init__(self, ptm: PeerTaskManager, *, upload_addr: str = ""):
        self.ptm = ptm
        self.upload_addr = upload_addr

    # -- local API -----------------------------------------------------

    async def download(self, request: DownloadRequest, context) -> AsyncIterator:
        async for resp in self.ptm.start_file_task(request):
            yield resp

    async def stat_task(self, request: StatTaskDaemonRequest, context) -> TaskStat:
        task_id = request.task_id or self.ptm._task_id(
            request.url, request.url_meta or UrlMeta())
        return await self.ptm.stat_task(task_id, local_only=request.local_only)

    async def import_task(self, request: ImportTaskRequest, context) -> TaskStat:
        task_id = await self.ptm.import_file(
            request.path, request.url, request.url_meta,
            task_type=request.task_type)
        return await self.ptm.stat_task(task_id)

    async def export_task(self, request: ExportTaskRequest, context) -> Empty:
        await self.ptm.export_file(request.url, request.output,
                                   request.url_meta, local_only=request.local_only,
                                   timeout_s=request.timeout_s)
        return Empty()

    async def delete_task(self, request: DeleteTaskRequest, context) -> Empty:
        task_id = request.task_id or self.ptm._task_id(
            request.url, request.url_meta or UrlMeta())
        await self.ptm.delete_task(task_id)
        return Empty()

    # -- peer API ------------------------------------------------------

    async def get_piece_tasks(self, request: PieceTaskRequest, context) -> PiecePacket:
        ts = self.ptm.storage_mgr.get(request.task_id)
        conductor = self.ptm.conductor(request.task_id)
        if ts is None and conductor is not None:
            ts = conductor.storage
        if ts is None:
            raise DFError(Code.NOT_FOUND, f"task {request.task_id[:12]} unknown")
        infos = [p.to_info() for p in ts.piece_infos(request.start_num, request.limit)]
        md = ts.md
        return PiecePacket(task_id=request.task_id, dst_peer_id=request.dst_peer_id,
                           dst_addr=self.upload_addr, piece_infos=infos,
                           total_piece_count=md.total_piece_count,
                           content_length=md.content_length,
                           piece_size=md.piece_size)

    async def sync_piece_tasks(self, request_iter, context) -> AsyncIterator:
        """Bidi: each request asks for piece metadata; responses stream as
        pieces appear (push on piece arrival for running tasks)."""
        async for request in request_iter:
            conductor = self.ptm.conductor(request.task_id)
            sent: set[int] = set()
            packet = await self.get_piece_tasks(request, context)
            for p in packet.piece_infos or []:
                sent.add(p.piece_num)
            yield packet
            if conductor is None or conductor.done_event.is_set():
                continue
            # live task: push updates until done
            q = conductor.subscribe()
            try:
                while True:
                    event = await q.get()
                    if event["type"] == "piece" and event["num"] not in sent:
                        sent.add(event["num"])
                        refreshed = await self.get_piece_tasks(PieceTaskRequest(
                            task_id=request.task_id,
                            src_peer_id=request.src_peer_id,
                            dst_peer_id=request.dst_peer_id,
                            start_num=event["num"], limit=1), context)
                        yield refreshed
                    elif event["type"] == "done":
                        yield await self.get_piece_tasks(PieceTaskRequest(
                            task_id=request.task_id,
                            src_peer_id=request.src_peer_id,
                            dst_peer_id=request.dst_peer_id,
                            start_num=0, limit=0), context)
                        break
            finally:
                conductor.unsubscribe(q)

    # -- seeder API ----------------------------------------------------

    async def obtain_seeds(self, request: ObtainSeedsRequest,
                           context) -> AsyncIterator:
        """Trigger a seed download and stream piece announcements (legacy-CDN
        style interface the scheduler's seed-peer client consumes)."""
        conductor = await self.ptm.get_or_create_conductor(
            request.url, request.url_meta or UrlMeta())
        q = conductor.subscribe()
        try:
            # replay pieces already landed
            if conductor.storage is not None:
                for p in conductor.storage.piece_infos():
                    yield PieceSeed(peer_id=conductor.peer_id,
                                    piece_info=p.to_info(),
                                    content_length=conductor.content_length,
                                    total_piece_count=conductor.total_pieces)
            while True:
                event = await q.get()
                if event["type"] == "piece":
                    assert conductor.storage is not None
                    metas = conductor.storage.piece_infos(event["num"], 1)
                    if metas:
                        yield PieceSeed(peer_id=conductor.peer_id,
                                        piece_info=metas[0].to_info(),
                                        content_length=conductor.content_length,
                                        total_piece_count=conductor.total_pieces)
                elif event["type"] == "done":
                    if not event.get("success"):
                        raise DFError(Code(event.get("code") or Code.UNKNOWN),
                                      event.get("message", "seed failed"))
                    yield PieceSeed(peer_id=conductor.peer_id, done=True,
                                    content_length=conductor.content_length,
                                    total_piece_count=conductor.total_pieces)
                    return
        finally:
            conductor.unsubscribe(q)


def build_service(svc: DaemonService) -> list[ServiceDef]:
    d = ServiceDef(DAEMON_SERVICE)
    d.unary_stream("Download", svc.download)
    d.unary_unary("StatTask", svc.stat_task)
    d.unary_unary("ImportTask", svc.import_task)
    d.unary_unary("ExportTask", svc.export_task)
    d.unary_unary("DeleteTask", svc.delete_task)
    d.unary_unary("GetPieceTasks", svc.get_piece_tasks)
    d.stream_stream("SyncPieceTasks", svc.sync_piece_tasks)
    s = ServiceDef(SEEDER_SERVICE)
    s.unary_stream("ObtainSeeds", svc.obtain_seeds)
    return [d, s]
