"""Daemon gRPC services.

Role parity: reference ``client/daemon/rpcserver/rpcserver.go`` — the local
API (``Download`` server-stream, cache ops) and the peer API
(``GetPieceTasks``, ``SyncPieceTasks`` bidi, seeder ``ObtainSeeds``).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Any, AsyncIterator

from ..common.errors import Code, DFError
from ..idl.messages import (DeleteTaskRequest, DownloadRequest, Empty,
                            ExportTaskRequest, ImportTaskRequest,
                            ObtainSeedsRequest, PiecePacket, PieceSeed,
                            PieceTaskRequest, StatTaskDaemonRequest, TaskStat,
                            UrlMeta)
from ..rpc.server import RPCServer, ServiceDef
from .peertask_manager import PeerTaskManager

log = logging.getLogger("df.rpc.daemon")

DAEMON_SERVICE = "df.daemon.Daemon"
SEEDER_SERVICE = "df.daemon.Seeder"


class _SuperSeed:
    """Per-task super-seed announcement policy (seed daemons only).

    A seed that reveals every piece to every child turns a fan-out into a
    star: all children are starved on the origin-paced trickle and pull each
    fresh piece straight off the seed, so the seed's NIC bounds the whole
    swarm. Instead each piece is announced to at most ``fanout`` children
    (spread least-loaded-first), forcing further replication through the
    mesh. A rotation timer widens every piece by one more child per tick
    (capped, see ``_rotate``) so a slow or dead child can never strand a
    piece, a departing child's exclusive assignments return to the pool, and
    a child whose mesh parents have nothing for it pulls more via starvation
    pings (``reveal_to``). The fanout is deliberately a few, not 1 and not
    all: round 3 ran fanout=1 and starved the pipeline (children idled
    waiting for reveals — BENCH_r03 halved); full broadcast resurrects the
    star. Supply-side rationing is only the coarse filter now — the fine
    control is demand-side: children's dispatchers rank seed parents
    strictly last (piece_dispatcher.ParentState.rank) and the upload
    server 503s past
    its per-transfer concurrency, so revealed-but-mesh-available pieces are
    pulled from the mesh anyway. This is the classic BitTorrent
    "super-seeding" idea; the reference has no equivalent — its seeds
    announce everything (``rpcserver.go SyncPieceTasks``).
    """

    # Starvation-ping reveals are budgeted PER CHILD: a child running ahead
    # of the mesh is perpetually starving (nobody else has its frontier
    # pieces yet), pings constantly, and un-budgeted reveals turn it into
    # the seed's dedicated first tier — one child sourcing ~everything from
    # the seed (the round-4 max_seed_sourced_fraction outlier). Budgeted,
    # it waits a beat and the mesh catches up; the seed's egress spreads
    # evenly instead of concentrating.
    REVEAL_RATE_PER_S = 0.6
    REVEAL_BURST = 2.0

    def __init__(self, *, fanout: int = 2, rotate_interval_s: float = 0.5):
        self.fanout = fanout
        self.rotate_interval_s = rotate_interval_s
        self.known: set[int] = set()
        self.assigned: dict[int, set[str]] = {}   # piece -> peer ids told
        self.subs: dict[str, asyncio.Queue] = {}  # peer id -> allowed nums
        self.slices: dict[str, str] = {}          # peer id -> TPU slice
        self._reveal_budget: dict[str, Any] = {}  # peer id -> TokenBucket
        self._rotor: asyncio.Task | None = None

    def _load(self, peer_id: str) -> int:
        return sum(1 for owners in self.assigned.values() if peer_id in owners)

    def _offer(self, num: int, target: int | None = None) -> None:
        """Reveal ``num`` to up to fanout children — ONE PER SLICE first
        (TPU-native: each slice gets a local first-tier copy whose intra-
        slice ICI fan-out is ~free; revealing twice into one slice while
        another has no copy forces cross-DCN pulls for the whole other
        slice), least-loaded within a slice."""
        owners = self.assigned.setdefault(num, set())
        want = (self.fanout if target is None else target) - len(owners)
        if want <= 0:
            return
        covered = {self.slices.get(pid, "") for pid in owners}
        cands = sorted((s for s in self.subs if s not in owners),
                       key=self._load)
        picked: list[str] = []
        for pid in cands:               # pass 1: uncovered slices
            if len(picked) >= want:
                break
            sl = self.slices.get(pid, "")
            if sl not in covered:
                picked.append(pid)
                covered.add(sl)
        for pid in cands:               # pass 2: fill remaining fanout
            if len(picked) >= want:
                break
            if pid not in picked:
                picked.append(pid)
        for pid in picked:
            owners.add(pid)
            self.subs[pid].put_nowait(num)

    def on_piece(self, num: int) -> None:
        self.known.add(num)
        self._offer(num)

    def reveal_to(self, peer_id: str, n: int = 2) -> None:
        """Starvation pull: a child with idle workers and nothing
        dispatchable asked for more work. Reveal it up to ``n`` of the
        least-revealed pieces it doesn't know yet, within its per-child
        budget (see REVEAL_RATE_PER_S). This is the growth path for
        reveals — paced by actual mesh scarcity (a child the mesh feeds
        never pings), so seed egress converges to the demand the mesh
        cannot meet without any child making the seed its main parent."""
        q = self.subs.get(peer_id)
        if q is None:
            return
        budget = self._reveal_budget.get(peer_id)
        if budget is None:
            from ..common.rate import TokenBucket
            budget = self._reveal_budget[peer_id] = TokenBucket(
                self.REVEAL_RATE_PER_S, burst=self.REVEAL_BURST)
        cands = sorted(
            (num for num in self.known
             if peer_id not in self.assigned.get(num, ())),
            key=lambda num: len(self.assigned.get(num, ())))
        for num in cands[:n]:
            if not budget.try_acquire(1):
                return
            self.assigned.setdefault(num, set()).add(peer_id)
            q.put_nowait(num)

    def subscribe(self, peer_id: str, *, slice_name: str = "") -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self.subs[peer_id] = q
        if slice_name:
            self.slices[peer_id] = slice_name
        for num in self.known:   # fill any under-assigned pieces
            self._offer(num)
        if self._rotor is None:
            self._rotor = asyncio.get_running_loop().create_task(self._rotate())
        return q

    def unsubscribe(self, peer_id: str, q: asyncio.Queue | None = None) -> None:
        """``q`` guards reconnects: a child that re-subscribed on a new
        stream must not have its fresh subscription torn down by the OLD
        stream's cleanup (only the owner of the registered queue may
        remove it)."""
        if q is not None and self.subs.get(peer_id) is not q:
            return
        self.subs.pop(peer_id, None)
        self.slices.pop(peer_id, None)
        self._reveal_budget.pop(peer_id, None)
        for owners in self.assigned.values():
            owners.discard(peer_id)
        if not self.subs and self._rotor is not None:
            self._rotor.cancel()
            self._rotor = None

    async def _rotate(self) -> None:
        # liveness net for alive-but-slow assignees, CAPPED at 2x fanout: an
        # uncapped rotor converges to full broadcast whenever the swarm runs
        # slower than the timer (e.g. CPU-starved hosts), resurrecting the
        # star. Dead assignees are handled by unsubscribe() returning their
        # pieces to the pool, and truly stuck children by starvation pings.
        while True:
            await asyncio.sleep(self.rotate_interval_s)
            for num in list(self.known):
                have = len(self.assigned.get(num, ()))
                if have < 2 * self.fanout:
                    self._offer(num, target=have + 1)


class DaemonService:
    """Wire handlers; pure delegation to PeerTaskManager + storage."""

    def __init__(self, ptm: PeerTaskManager, *, upload_addr: str = ""):
        self.ptm = ptm
        self.upload_addr = upload_addr
        self._superseed: dict[str, _SuperSeed] = {}
        self._superseed_feeders: dict[str, asyncio.Task] = {}

    # -- local API -----------------------------------------------------

    async def download(self, request: DownloadRequest, context) -> AsyncIterator:
        if request.recursive:
            async for resp in self._download_recursive(request):
                yield resp
            return
        async for resp in self.ptm.start_file_task(request):
            yield resp

    async def _download_recursive(self, request: DownloadRequest
                                  ) -> AsyncIterator:
        """BFS a directory-shaped origin: one file task per leaf, outputs
        mirrored under ``request.output``, up to ``recursive_concurrency``
        leaves in flight (reference ``client/dfget/dfget.go:317``
        recursiveDownload; daemon-side recursion per ``rpcserver.go:404``).
        Progress events from concurrent tasks interleave on the stream;
        each file still emits its own done event."""
        from ..source.client import walk

        meta = request.url_meta
        if meta is not None and (meta.digest or meta.range):
            # a whole-tree digest/range can't apply to each file
            from dataclasses import replace as _dc_replace
            meta = _dc_replace(meta, digest="", range="")
        header = dict(meta.header) if meta is not None and meta.header else None
        sem = asyncio.Semaphore(max(1, request.recursive_concurrency))
        out_q: asyncio.Queue = asyncio.Queue()
        _DONE = object()

        async def fetch(entry, rel: str) -> None:
            async with sem:
                sub = DownloadRequest(
                    url=entry.url, output=os.path.join(request.output, rel),
                    url_meta=meta, timeout_s=request.timeout_s,
                    disable_back_source=request.disable_back_source,
                    device_sink=request.device_sink,
                    task_type=request.task_type,
                    rate_limit_bps=request.rate_limit_bps,
                    keep_original_offset=request.keep_original_offset)
                async for resp in self.ptm.start_file_task(sub):
                    # dflint: disable=DF005 — out_q is unbounded, put() never parks; the sem intentionally spans the whole leaf download to bound fan-out
                    await out_q.put(resp)

        async def produce() -> None:
            tasks: list[asyncio.Task] = []
            try:
                async for entry, rel in walk(
                        request.url, timeout_s=request.timeout_s,
                        header=header):
                    tasks.append(asyncio.get_running_loop().create_task(
                        fetch(entry, rel)))
                results = await asyncio.gather(*tasks,
                                               return_exceptions=True)
                errs = [r for r in results if isinstance(r, BaseException)]
                if errs:
                    raise errs[0]
            finally:
                for t in tasks:
                    if not t.done():
                        t.cancel()
                await out_q.put(_DONE)

        producer = asyncio.get_running_loop().create_task(produce())
        try:
            while True:
                item = await out_q.get()
                if item is _DONE:
                    break
                yield item
            await producer   # surface listing/fetch errors to the stream
        finally:
            if not producer.done():   # consumer died early (client gone)
                producer.cancel()
                try:
                    await producer
                # dflint: disable=DF004 — cancel-and-reap: we JUST cancelled the producer while unwinding; its CancelledError must not mask the original exception
                except BaseException:  # noqa: BLE001 - already unwinding
                    pass

    async def stat_task(self, request: StatTaskDaemonRequest, context) -> TaskStat:
        task_id = request.task_id or self.ptm._task_id(
            request.url, request.url_meta or UrlMeta())
        return await self.ptm.stat_task(task_id, local_only=request.local_only)

    async def import_task(self, request: ImportTaskRequest, context) -> TaskStat:
        task_id = await self.ptm.import_file(
            request.path, request.url, request.url_meta,
            task_type=request.task_type)
        return await self.ptm.stat_task(task_id)

    async def export_task(self, request: ExportTaskRequest, context) -> Empty:
        await self.ptm.export_file(request.url, request.output,
                                   request.url_meta, local_only=request.local_only,
                                   timeout_s=request.timeout_s)
        return Empty()

    async def delete_task(self, request: DeleteTaskRequest, context) -> Empty:
        task_id = request.task_id or self.ptm._task_id(
            request.url, request.url_meta or UrlMeta())
        await self.ptm.delete_task(task_id)
        return Empty()

    # -- peer API ------------------------------------------------------

    def _relay_ahead(self, task_id: str, known: set[int],
                     start_num: int = 0) -> list:
        """Announce-ahead infos: pieces IN-FLIGHT on this daemon right now
        (daemon/relay.py spans). A child that pulls one is served to the
        landing watermark by the upload server's streaming path — this is
        the control-plane half of cut-through relay."""
        relay = getattr(self.ptm, "relay", None)
        if relay is None:
            return []
        return [i for i in relay.inflight_infos(task_id)
                if i.piece_num not in known and i.piece_num >= start_num]

    async def get_piece_tasks(self, request: PieceTaskRequest, context) -> PiecePacket:
        ts = self.ptm.storage_mgr.get(request.task_id)
        conductor = self.ptm.conductor(request.task_id)
        if ts is None and conductor is not None:
            ts = conductor.storage
        if ts is None:
            raise DFError(Code.NOT_FOUND, f"task {request.task_id[:12]} unknown")
        infos = [p.to_info() for p in ts.piece_infos(request.start_num, request.limit)]
        md = ts.md
        ahead = self._relay_ahead(request.task_id,
                                  {p.piece_num for p in infos}
                                  | set(md.pieces),
                                  request.start_num)
        return PiecePacket(task_id=request.task_id, dst_peer_id=request.dst_peer_id,
                           dst_addr=self.upload_addr,
                           piece_infos=infos + ahead,
                           total_piece_count=md.total_piece_count,
                           content_length=md.content_length,
                           piece_size=md.piece_size,
                           progress=len(md.pieces),
                           relay_nums=([i.piece_num for i in ahead]
                                       or None))

    def _storage_for(self, task_id: str, conductor):
        ts = self.ptm.storage_mgr.get(task_id)
        if ts is None and conductor is not None:
            ts = conductor.storage
        return ts

    def _packet_for_nums(self, request: PieceTaskRequest, conductor,
                         nums: list[int],
                         relay_nums: list[int] | None = None,
                         ) -> PiecePacket | None:
        """Announcement packet carrying exactly ``nums`` (batch push) plus
        any still-in-flight ``relay_nums`` (announce-ahead)."""
        ts = self._storage_for(request.task_id, conductor)
        if ts is None:
            return None
        # direct dict lookups — a 70B-weights task has ~17k pieces and this
        # runs per announcement wakeup per subscriber
        infos = []
        for n in nums:
            p = ts.md.pieces.get(n)
            if p is not None:
                infos.append(p.to_info())
        ahead = []
        if relay_nums:
            live = {i.piece_num: i
                    for i in self._relay_ahead(request.task_id,
                                               set(ts.md.pieces))}
            for n in relay_nums:
                p = ts.md.pieces.get(n)
                if p is not None:
                    infos.append(p.to_info())   # landed while queued
                elif n in live:
                    ahead.append(live[n])
                # else: the span died between the event and this packet
                # (failed transfer / corrupt landing) — dropped from the
                # packet; the caller un-marks it as sent so the eventual
                # landing re-announces it with a digest
        md = ts.md
        return PiecePacket(task_id=request.task_id,
                           dst_peer_id=request.dst_peer_id,
                           dst_addr=self.upload_addr,
                           piece_infos=infos + ahead,
                           total_piece_count=md.total_piece_count,
                           content_length=md.content_length,
                           piece_size=md.piece_size,
                           progress=len(md.pieces),
                           relay_nums=([i.piece_num for i in ahead]
                                       or None))

    @staticmethod
    def _drain(q: asyncio.Queue, first) -> list:
        """One awaited event + everything already queued behind it: under
        load announcements batch into one packet per wakeup instead of one
        per piece (the per-message overhead is what saturates a host fanning
        out to many children)."""
        events = [first]
        while True:
            try:
                events.append(q.get_nowait())
            except asyncio.QueueEmpty:
                return events

    async def sync_piece_tasks(self, request_iter, context) -> AsyncIterator:
        """Bidi: each request asks for piece metadata; responses stream as
        pieces appear (push on piece arrival for running tasks, batched per
        wakeup). Seed daemons route announcements through the super-seed
        policy instead of broadcasting everything."""
        # sent survives ACROSS requests on one stream: follow-up requests are
        # starvation pings, and answering each with the full piece list again
        # (the old per-request reset) turns a starving swarm into an
        # announcement flood — 10Hz x parents x children of full packets
        sent: set[int] = set()
        first_packet = True
        async for request in request_iter:
            conductor = self.ptm.conductor(request.task_id)
            if self.ptm.is_seed:
                async for packet in self._sync_superseed(request, request_iter,
                                                         conductor, context):
                    yield packet
                continue
            packet = await self.get_piece_tasks(request, context)
            packet.piece_infos = [p for p in packet.piece_infos or []
                                  if p.piece_num not in sent]
            for p in packet.piece_infos:
                sent.add(p.piece_num)
            if packet.piece_infos or first_packet:
                first_packet = False
                yield packet
            if conductor is None or conductor.done_event.is_set():
                continue
            # live task: push updates until done
            q = conductor.subscribe()
            try:
                done = False
                while not done:
                    events = self._drain(q, await q.get())
                    nums: list[int] = []
                    relay_nums: list[int] = []
                    for event in events:
                        if (event["type"] == "piece"
                                and event["num"] not in sent):
                            sent.add(event["num"])
                            nums.append(event["num"])
                        elif event["type"] == "relay":
                            # announce-ahead: these pieces are arriving on
                            # this daemon NOW — a child may begin pulling
                            # them against the landing watermark
                            for nn in event["nums"]:
                                if nn not in sent:
                                    sent.add(nn)
                                    relay_nums.append(nn)
                        elif event["type"] == "done":
                            done = True
                    if (nums or relay_nums) and not done:
                        refreshed = self._packet_for_nums(
                            request, conductor, nums,
                            relay_nums=relay_nums)
                        if refreshed is not None:
                            announced = {p.piece_num for p in
                                         refreshed.piece_infos or []}
                            for nn in relay_nums:
                                if nn not in announced:
                                    sent.discard(nn)
                            if refreshed.piece_infos:
                                yield refreshed
                    elif done:
                        yield await self.get_piece_tasks(PieceTaskRequest(
                            task_id=request.task_id,
                            src_peer_id=request.src_peer_id,
                            dst_peer_id=request.dst_peer_id,
                            start_num=0, limit=0), context)
            finally:
                conductor.unsubscribe(q)

    def _superseed_for(self, task_id: str, conductor) -> _SuperSeed:
        policy = self._superseed.get(task_id)
        if policy is None:
            policy = self._superseed[task_id] = _SuperSeed()
            ts = self.ptm.storage_mgr.get(task_id)
            if ts is None and conductor is not None:
                ts = conductor.storage
            if ts is not None:
                for p in ts.piece_infos():
                    policy.known.add(p.num)
            if conductor is not None and not conductor.done_event.is_set():
                self._superseed_feeders[task_id] = (
                    asyncio.get_running_loop().create_task(
                        self._feed_superseed(task_id, policy, conductor)))
        return policy

    @staticmethod
    async def _feed_superseed(task_id: str, policy: _SuperSeed,
                              conductor) -> None:
        q = conductor.subscribe()
        try:
            while True:
                event = await q.get()
                if event["type"] == "piece":
                    policy.on_piece(event["num"])
                elif event["type"] == "done":
                    return
        finally:
            conductor.unsubscribe(q)

    async def _sync_superseed(self, request: PieceTaskRequest, request_iter,
                              conductor, context) -> AsyncIterator:
        policy = self._superseed_for(request.task_id, conductor)
        sq = policy.subscribe(request.src_peer_id,
                              slice_name=request.src_slice)

        async def read_pings() -> None:
            # any follow-up request on the stream = "my workers are idle and
            # I have nothing dispatchable" — reveal this child more pieces
            async for _ in request_iter:
                policy.reveal_to(request.src_peer_id)

        pings = asyncio.get_running_loop().create_task(read_pings())
        try:
            # geometry-only opener (no piece list): the child needs sizes to
            # set up its store before any piece is revealed to it
            base = await self.get_piece_tasks(PieceTaskRequest(
                task_id=request.task_id, src_peer_id=request.src_peer_id,
                dst_peer_id=request.dst_peer_id, start_num=0, limit=1),
                context)
            base.piece_infos = []
            yield base
            while True:
                nums = self._drain(sq, await sq.get())
                packet = self._packet_for_nums(request, conductor, nums)
                if packet is not None:
                    yield packet
        finally:
            pings.cancel()
            policy.unsubscribe(request.src_peer_id, sq)
            # last subscriber gone: evict the policy + feeder, or a
            # long-lived seed leaks one _SuperSeed (known/assigned sets)
            # and a finished feeder entry per task ever served. A later
            # subscriber recreates both from storage.
            if not policy.subs:
                self._superseed.pop(request.task_id, None)
                feeder = self._superseed_feeders.pop(request.task_id, None)
                if feeder is not None:
                    feeder.cancel()

    # -- seeder API ----------------------------------------------------

    async def obtain_seeds(self, request: ObtainSeedsRequest,
                           context) -> AsyncIterator:
        """Trigger a seed download and stream piece announcements (legacy-CDN
        style interface the scheduler's seed-peer client consumes)."""
        conductor = await self.ptm.get_or_create_conductor(
            request.url, request.url_meta or UrlMeta())
        q = conductor.subscribe()
        try:
            # replay pieces already landed
            if conductor.storage is not None:
                for p in conductor.storage.piece_infos():
                    yield PieceSeed(peer_id=conductor.peer_id,
                                    piece_info=p.to_info(),
                                    content_length=conductor.content_length,
                                    total_piece_count=conductor.total_pieces)
            while True:
                event = await q.get()
                if event["type"] == "piece":
                    assert conductor.storage is not None
                    metas = conductor.storage.piece_infos(event["num"], 1)
                    if metas:
                        yield PieceSeed(peer_id=conductor.peer_id,
                                        piece_info=metas[0].to_info(),
                                        content_length=conductor.content_length,
                                        total_piece_count=conductor.total_pieces)
                elif event["type"] == "done":
                    if not event.get("success"):
                        raise DFError(Code(event.get("code") or Code.UNKNOWN),
                                      event.get("message", "seed failed"))
                    yield PieceSeed(peer_id=conductor.peer_id, done=True,
                                    content_length=conductor.content_length,
                                    total_piece_count=conductor.total_pieces)
                    return
        finally:
            conductor.unsubscribe(q)


def build_service(svc: DaemonService) -> list[ServiceDef]:
    d = ServiceDef(DAEMON_SERVICE)
    d.unary_stream("Download", svc.download)
    d.unary_unary("StatTask", svc.stat_task)
    d.unary_unary("ImportTask", svc.import_task)
    d.unary_unary("ExportTask", svc.export_task)
    d.unary_unary("DeleteTask", svc.delete_task)
    d.unary_unary("GetPieceTasks", svc.get_piece_tasks)
    d.stream_stream("SyncPieceTasks", svc.sync_piece_tasks)
    s = ServiceDef(SEEDER_SERVICE)
    s.unary_stream("ObtainSeeds", svc.obtain_seeds)
    return [d, s]
