"""Daemon configuration.

Role parity: reference ``client/config/peerhost.go`` (DaemonOption tree),
trimmed to the knobs this implementation actually honors.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..common.unit import MiB
from .qos import QosSection


@dataclass
class SchedulerConfig:
    addresses: list[str] = field(default_factory=list)  # empty -> no scheduler (back-source only)
    register_timeout_s: float = 10.0
    schedule_timeout_s: float = 30.0       # max wait for a usable peer packet
    max_reschedule: int = 5                # reference RetryLimit
    # register failover ladder (docs/RESILIENCE.md): a dead hashed
    # scheduler fails over to the next ring members before the task goes
    # to origin, and the dead address is demoted for demote_s so later
    # tasks skip it until a probe revives it
    failover_n: int = 3                    # ring members tried per register
    demote_s: float = 30.0                 # sticky demotion window
    # manager-discovered scheduler set refresh cadence (reference daemon
    # dynconfig refresh): 0 disables. A scheduler replaced — or one that
    # registers AFTER this daemon booted — must reach daemons without a
    # daemon restart.
    refresh_interval_s: float = 30.0


@dataclass
class SecurityConfig:
    """Fleet mTLS via manager-issued certs (reference pkg/issuer +
    certify-style auto-issuance in client/daemon/daemon.go:367-458)."""

    enabled: bool = False
    issue_token: str = ""             # manager issuer.token (out of band)
    issue_token_path: str = ""        # or a file holding it
    ca_cert: str = ""                 # fleet CA path (manager proxy-ca.crt)
    cert_validity_s: int = 7 * 24 * 3600
    # TLS rollout policy for BOTH peer planes — the gRPC port and the
    # HTTPS piece-upload port (reference pkg/rpc/mux.go + credential.go):
    # "force" = TLS only; "default"/"prefer" = plaintext AND TLS accepted
    # on the one port so a live fleet can upgrade without a flag day
    # ("prefer" flags plaintext peers in logs/metrics)
    tls_policy: str = "force"

    def validate(self) -> None:
        if self.tls_policy not in ("default", "prefer", "force"):
            raise ValueError(
                f"security.tls_policy must be default|prefer|force, "
                f"got {self.tls_policy!r}")
    # NOTE scope: with security enabled, BOTH peer planes are mTLS — the
    # gRPC sync streams and the HTTPS piece uploads (client certs required
    # on each). The renewal loop refreshes the issued material at 2/3
    # validity: outbound channels/sessions pick it up as they rotate;
    # LISTENERS load certs at bind time and need a daemon restart within
    # the validity window (default 7d) to serve the fresh leaf.


@dataclass
class TracingConfig:
    enabled: bool = False
    jsonl_path: str = ""              # "" -> <workdir>/logs/traces.jsonl
    otlp_endpoint: str = ""           # e.g. http://collector:4318
    sample_ratio: float = 1.0


@dataclass
class FlightConfig:
    """Download flight recorder (daemon/flight_recorder.py): per-task
    piece-lifecycle journal behind GET /debug/flight on the upload port.
    On by default — recording is one deque append per piece event and
    memory is ring-capped; disabling removes even that."""

    enabled: bool = True
    max_tasks: int = 64               # flights kept (drop-oldest)
    max_events: int = 4096            # events per flight (ring)
    max_serves: int = 1024            # serve-side edge rows per flight
    # (ring; a hot seed fans one task out to the whole pod, so the serve
    # journal is bounded separately from the download journal)


@dataclass
class HealthSection:
    """Runtime health plane (common/health.py): event-loop lag sampler +
    coroutine watchdog + per-stage SLO budgets behind GET /debug/health.
    On by default — the monitor is one coroutine ticking at
    ``sample_interval_s`` and sections are a dict insert per piece group."""

    enabled: bool = True
    sample_interval_s: float = 0.1     # lag sample / watchdog sweep period
    stall_threshold_s: float = 1.0     # loop lag past this = stall event
    dump_min_interval_s: float = 10.0  # stack-dump rate limit
    # SLO budgets (ms) per download stage; <= 0 disables that budget
    slo_schedule_ms: float = 1000.0
    slo_first_byte_ms: float = 2000.0
    slo_wire_ms: float = 5000.0
    slo_hbm_ms: float = 1000.0

    def to_plane(self):
        from ..common.health import HealthConfig
        return HealthConfig(
            enabled=self.enabled,
            sample_interval_s=self.sample_interval_s,
            stall_threshold_s=self.stall_threshold_s,
            dump_min_interval_s=self.dump_min_interval_s,
            slo_schedule_ms=self.slo_schedule_ms,
            slo_first_byte_ms=self.slo_first_byte_ms,
            slo_wire_ms=self.slo_wire_ms,
            slo_hbm_ms=self.slo_hbm_ms)


@dataclass
class PexConfig:
    """Peer-exchange gossip plane (daemon/pex.py): decentralized piece
    discovery backing the ``pex`` degradation-ladder rung. On by default —
    a round is a handful of small HTTP exchanges every ``interval_s``
    (jittered), and with no known peers it is a no-op."""

    enabled: bool = True
    interval_s: float = 5.0           # gossip cadence (x0.6-1.4 jitter)
    fanout: int = 3                   # peers pushed to per round
    ttl_s: float = 60.0               # swarm-index entry lifetime
    bootstrap: list[str] = field(default_factory=list)  # ip:upload_port seeds
    max_digest_tasks: int = 256       # tasks advertised per digest
    # cross-pod federation (ROADMAP item 2): full piece-set digests stay
    # pod-scoped when the host knows its pod (pod_scope); an OPERATOR-
    # DESIGNATED summary seed (pod_seed — deliberately static config,
    # independent of the scheduler's per-task routing election, so
    # summary exchange survives a scheduler outage; designate >= 2 per
    # pod) additionally exchanges the compact completeness summary with
    # the other pods' summary seeds listed in federation_peers
    # (ip:upload_port) — gossip bytes then scale with the pod, not the
    # fleet (docs/RESILIENCE.md "Cross-pod federation")
    pod_scope: bool = True
    pod_seed: bool = False
    federation_peers: list[str] = field(default_factory=list)


@dataclass
class DownloadConfig:
    piece_parallelism: int = 4             # piece download workers per task
    back_source_parallelism: int = 4       # concurrent origin range streams
    back_source_group_min_bytes: int = 32 * MiB  # below this, one stream
    total_rate_limit_bps: int = 0          # 0 = unlimited
    per_peer_rate_limit_bps: int = 0
    traffic_shaper_kind: str = "sampling"  # sampling | plain
    prefetch_whole_file: bool = False      # ranged requests warm the whole task
    first_piece_timeout_s: float = 30.0
    piece_timeout_s: float = 60.0
    # TLS trust for https origins (private registries / custom CAs)
    source_ca: str = ""                    # extra CA bundle path
    source_insecure: bool = False          # disable verification (tests)
    # cut-through relay (daemon/relay.py): serve a piece while it is still
    # arriving. ON by default — disarmed it costs one attribute store per
    # downloaded chunk; off restores strict store-and-forward (the upload
    # server then 416s incomplete ranges exactly as before)
    relay_enabled: bool = True
    # how long a streaming serve waits for the landing watermark to move
    # before giving up (per wait, reset on every advance) — bounds a serve
    # whose upstream wedged so the child's own piece deadline, not a
    # leaked upload slot, decides the requeue
    relay_stall_s: float = 10.0


@dataclass
class UploadConfig:
    port: int = 0                          # 0 = ephemeral
    rate_limit_bps: int = 0
    concurrent_limit: int = 0              # 0 = scheduler's per-type default
    debug_endpoints: bool = False          # /debug/{stacks,profile} (pprof)
    # upload slots a `bulk`-class child may hold at once (QoS): the
    # remainder stays reserved for critical/standard children, so a bulk
    # herd can saturate its share of the gate without ever 503ing the
    # foreground. 0 = derive (concurrent limit minus two, floor 1).
    bulk_concurrent_limit: int = 0


@dataclass
class StorageSection:
    task_ttl_s: float = 6 * 3600.0
    disk_gc_high_ratio: float = 0.90
    disk_gc_low_ratio: float = 0.80
    capacity_bytes: int = 0
    gc_interval_s: float = 60.0
    # content-addressed store (storage/castore.py): cross-task dedupe
    # (a piece already held under any task is placed, not transferred;
    # identical completed content hardlink-coalesces to one inode). Off
    # restores strict task-id-keyed storage.
    dedupe_enabled: bool = True
    # crc32c re-verification of reloaded pieces at boot (off-loop) before
    # the warm state is advertised to the swarm
    reload_verify: bool = True
    # serve-popularity decay half-life feeding GC eviction order
    popularity_halflife_s: float = 600.0


@dataclass
class ProxyConfig:
    enabled: bool = False
    port: int = 0
    registry_mirror: str = ""              # upstream registry URL
    rules: list[str] = field(default_factory=list)  # regexes routed via P2P
    direct_rules: list[str] = field(default_factory=list)
    # HTTPS interception (reference proxy/cert.go + proxy.go:268): CONNECTs
    # to hijack-matching hosts are MITM'd with a CA-signed per-host leaf so
    # TLS registry pulls ride the mesh instead of bypassing it in a blind
    # tunnel. Empty hijack_hosts + hijack=True intercepts everything.
    hijack: bool = False
    hijack_hosts: list[str] = field(default_factory=list)   # host regexes
    ca_cert: str = ""                      # PEM paths; empty -> auto-CA in
    ca_key: str = ""                       # the daemon workdir
    # SNI listener (reference proxy_sni.go): transparent-TLS port for
    # clients that resolve the registry straight to this daemon (no proxy
    # config needed); 0 disables, -1 binds an ephemeral port
    sni_port: int = 0
    # upstream TLS verification for intercepted fetches; disable only for
    # self-signed upstreams in tests
    verify_upstream: bool = True


@dataclass
class ObjectStorageConfig:
    enabled: bool = False
    port: int = 0
    # bucket name -> source-client base URL (file:///path, http(s)://,
    # gs://, s3://) — the P2P-accelerated READ path
    buckets: dict[str, str] = field(default_factory=dict)
    # bucket name -> backend client config for the WRITE path
    # ({kind: file|s3, base, bucket, access_key, secret_key, region};
    # reference pkg/objectstorage backends). file:// read buckets get an
    # implicit file backend.
    backends: dict[str, dict] = field(default_factory=dict)


@dataclass
class DaemonConfig:
    workdir: str = ""
    host_ip: str = ""                      # advertised to peers/scheduler
    listen_ip: str = "0.0.0.0"             # servers bind here (may differ under NAT)
    hostname: str = ""
    is_seed: bool = False
    rpc_port: int = 0                      # peer gRPC (0 = ephemeral)
    unix_sock: str = ""                    # local API socket path
    manager_addresses: list[str] = field(default_factory=list)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    download: DownloadConfig = field(default_factory=DownloadConfig)
    upload: UploadConfig = field(default_factory=UploadConfig)
    storage: StorageSection = field(default_factory=StorageSection)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    flight: FlightConfig = field(default_factory=FlightConfig)
    health: HealthSection = field(default_factory=HealthSection)
    pex: PexConfig = field(default_factory=PexConfig)
    security: SecurityConfig = field(default_factory=SecurityConfig)
    proxy: ProxyConfig = field(default_factory=ProxyConfig)
    object_storage: ObjectStorageConfig = field(default_factory=ObjectStorageConfig)
    # multi-tenant QoS admission + brownout (daemon/qos.py; see
    # docs/RESILIENCE.md "QoS and graceful brownout")
    qos: QosSection = field(default_factory=QosSection)
    announce_interval_s: float = 30.0
    probe_enabled: bool = True             # RTT probing via SyncProbes
    metrics_port: int = 0                  # 0 = disabled
    plugin_dir: str = ""                   # df_plugin_source_*.py schemes
