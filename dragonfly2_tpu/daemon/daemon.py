"""Daemon bootstrap: assemble storage, piece engine, servers; serve.

Role parity: reference ``client/daemon/daemon.go`` ``New``/``Serve`` — wires
the listeners (local API gRPC on unix socket, peer gRPC on TCP, upload HTTP,
optional proxy/object-gateway HTTP), the GC loop, the announcer, and the
scheduler client.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import socket
from typing import Any

from ..common.dfpath import DFPath
from ..common.errors import Code, DFError
from ..common.gc import GC, GCTask
from ..idl.messages import DeviceSink, Host, HostType
from ..storage.manager import StorageConfig, StorageManager
from ..tpu import topology
from .config import DaemonConfig
from .peertask_manager import PeerTaskManager
from .piece_manager import PieceManager
from ..rpc.client import ChannelPool
from .piece_downloader import PieceDownloader
from .piece_engine import PieceEngine
from .rpcserver import DaemonService, build_service
from .scheduler_session import SchedulerConnector
from .traffic_shaper import TrafficShaper
from .upload_server import UploadServer
from ..rpc.server import RPCServer

log = logging.getLogger("df.core.daemon")


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class Daemon:
    def __init__(self, cfg: DaemonConfig, *, scheduler_factory: Any = None,
                 p2p_engine_factory: Any = None):
        self.cfg = cfg
        self.hostname = cfg.hostname or socket.gethostname()
        self.host_ip = cfg.host_ip or _local_ip()
        self.paths = DFPath(cfg.workdir) if cfg.workdir else DFPath()
        self.paths.ensure()
        self.topology = topology.detect()
        self.storage_mgr = StorageManager(StorageConfig(
            data_dir=os.path.join(self.paths.data_dir, "tasks"),
            task_ttl_s=cfg.storage.task_ttl_s,
            disk_gc_high_ratio=cfg.storage.disk_gc_high_ratio,
            disk_gc_low_ratio=cfg.storage.disk_gc_low_ratio,
            capacity_bytes=cfg.storage.capacity_bytes,
            gc_interval_s=cfg.storage.gc_interval_s,
            dedupe_enabled=cfg.storage.dedupe_enabled,
            reload_verify=cfg.storage.reload_verify,
            popularity_halflife_s=cfg.storage.popularity_halflife_s))
        self.piece_mgr = PieceManager(cfg.download)
        self.shaper = TrafficShaper(
            total_rate_bps=cfg.download.total_rate_limit_bps,
            kind=cfg.download.traffic_shaper_kind)
        # multi-tenant QoS: class-aware admission + brownout shed
        # (daemon/qos.py); the shaper rides along for /debug/qos's
        # per-class rate readout
        from .qos import QosGovernor
        self.qos = QosGovernor(cfg.qos, shaper=self.shaper)
        # per-parent verdict ledger (daemon/verdicts.py): the local half
        # of the swarm immune system — typed failure memory consulted by
        # the engine's parent admission, the PEX rung, and self-quarantine
        from .verdicts import VerdictLedger
        self.verdicts = VerdictLedger()
        if self.storage_mgr.castore is not None:
            self.storage_mgr.castore.on_rot = lambda tid: \
                self.verdicts.self_quarantine(
                    f"cas placement re-verify failed (task {tid[:12]})")
        from .flight_recorder import FlightRecorder
        self.flight_recorder = FlightRecorder(
            enabled=cfg.flight.enabled, max_tasks=cfg.flight.max_tasks,
            max_events=cfg.flight.max_events,
            max_serves=cfg.flight.max_serves)
        # PEX gossip plane (daemon/pex.py): swarm index + gossiper exist
        # before the upload server so its routes mount at start; ports and
        # topology resolve lazily through host_info()
        # cut-through relay hub (daemon/relay.py): in-flight landing spans
        # the upload server serves to the watermark; exists before the
        # upload server and the engine factory so both share it
        self.relay = None
        if cfg.download.relay_enabled:
            from .relay import RelayHub
            self.relay = RelayHub()
        self.pex = None
        if cfg.pex.enabled:
            from .pex import PexGossiper
            from .swarm_index import SwarmIndex
            self.pex = PexGossiper(
                storage_mgr=self.storage_mgr,
                host_info=self.host_info,
                index=SwarmIndex(ttl_s=cfg.pex.ttl_s),
                interval_s=cfg.pex.interval_s, fanout=cfg.pex.fanout,
                max_digest_tasks=cfg.pex.max_digest_tasks,
                bootstrap=cfg.pex.bootstrap, relay=self.relay,
                verdicts=self.verdicts,
                pod_scope=cfg.pex.pod_scope,
                pod_seed=cfg.pex.pod_seed,
                federation_peers=cfg.pex.federation_peers)
        self.upload_server = UploadServer(
            self.storage_mgr, port=cfg.upload.port,
            rate_limit_bps=cfg.upload.rate_limit_bps,
            debug_endpoints=cfg.upload.debug_endpoints,
            concurrent_limit=cfg.upload.concurrent_limit,
            bulk_concurrent_limit=cfg.upload.bulk_concurrent_limit,
            host=cfg.listen_ip, flight_recorder=self.flight_recorder,
            pex=self.pex, relay=self.relay,
            relay_stall_s=cfg.download.relay_stall_s, qos=self.qos,
            verdicts=self.verdicts)
        # scopes the upload.serve faultgate key (byzantine chaos) to THIS
        # daemon even when several share one process (the test pod)
        self.upload_server.host_id = f"{self.hostname}-{self.host_ip}"
        self._scheduler_factory = scheduler_factory
        self._p2p_engine_factory = p2p_engine_factory
        self.scheduler: Any = None
        self.ptm: PeerTaskManager | None = None
        self.rpc: RPCServer | None = None
        self.local_rpc: RPCServer | None = None
        self.gc = GC()
        self.proxy_server: Any = None
        self.object_gateway: Any = None
        self.announcer: Any = None
        self.prober: Any = None
        self.manager: Any = None
        self.health: Any = None

    # ------------------------------------------------------------------

    def host_info(self) -> Host:
        return Host(
            id=f"{self.hostname}-{self.host_ip}",
            ip=self.host_ip, hostname=self.hostname,
            port=self.rpc.port if self.rpc else 0,
            download_port=self.upload_server.port,
            type=HostType.SUPER_SEED if self.cfg.is_seed else HostType.NORMAL,
            os=os.uname().sysname.lower(), platform=os.uname().machine,
            topology=self.topology,
            concurrent_upload_limit=self.cfg.upload.concurrent_limit,
            # self-quarantine rides every register AND announce: the
            # scheduler's quarantine registry treats the flag as hard
            # evidence (this daemon verified its own bit-rot)
            quarantined=self.verdicts.self_quarantined)

    def device_sink_builder(self, spec: DeviceSink):
        """Returns a factory(content_length[, shard_specs]) -> DeviceIngest
        honoring the request's sink spec. ``shard_specs`` (sharded tasks,
        common/sharding.py) switches the sink to manifest mode: named
        uneven shards that each become a device array the moment their
        bytes are covered."""
        def factory(content_length: int, shard_specs: list | None = None):
            if not topology.ensure_runtime_alive():
                # permanently poisoned (our own probe thread is parked in
                # jax init holding its locks), host-marked wedged, or a
                # fresh bounded probe just timed out: a bare jax call here
                # would hang the EVENT LOOP, not just this task — refuse
                # and let the caller fall back to disk-only. A recovered
                # runtime is re-admitted by the bounded probe.
                raise DFError(
                    Code.UNAVAILABLE,
                    "accelerator runtime is not answering; device sink "
                    "unavailable")
            import jax

            from ..tpu.hbm_sink import DeviceIngest
            if shard_specs:
                return DeviceIngest(content_length, dtype=spec.dtype,
                                    shard_specs=shard_specs)
            spd = spec.pipeline_shards
            if spd <= 0:
                # auto: one shard per DMA unit. Measured on the real chip:
                # smaller units lose (8 MiB ≈ serial, 16-per-file
                # pathological); the overlap comes from back-source's
                # front-to-back work-queue coverage completing these units
                # progressively, not from shrinking them.
                from ..common.piece import INGEST_DMA_UNIT_BYTES
                per_dev = -(-content_length // len(jax.devices()))
                spd = max(1, min(32, per_dev // INGEST_DMA_UNIT_BYTES))
            return DeviceIngest(content_length, dtype=spec.dtype,
                                shards_per_device=spd)
        return factory

    async def _enroll_security(self):
        from ..rpc.security import obtain_certificate
        from ..rpc.server import TLSOptions

        sec = self.cfg.security
        token = sec.issue_token
        if not token and sec.issue_token_path:
            # dflint: disable=DF001 — one-shot KB token read during startup enrollment, before the daemon serves traffic
            with open(sec.issue_token_path, encoding="utf-8") as f:
                # dflint: disable=DF001 — see above: startup enrollment
                token = f.read().strip()
        if not sec.ca_cert:
            log.warning(
                "security: enrolling over a channel with NO pinned fleet "
                "CA — the issuance token travels unprotected and the CA is "
                "trust-on-first-use; set security.ca_cert (and a TLS "
                "manager port) for untrusted networks")
        cert, key, ca = await obtain_certificate(
            self.cfg.manager_addresses,
            hosts=[self.host_ip, self.hostname],
            token=token, out_dir=os.path.join(self.paths.cache_dir, "tls"),
            validity_s=sec.cert_validity_s, tls_ca=sec.ca_cert)
        self.fleet_ca = sec.ca_cert or ca
        # peer channels verify the CA AND present our leaf; the server
        # REQUIRES client certs — that is the mutual half of mTLS
        self._peer_tls_ca = self.fleet_ca
        self._peer_tls_cert = cert
        self._peer_tls_key = key
        loop = asyncio.get_running_loop()
        self._cert_renewal = loop.create_task(self._renew_certs_loop())
        return TLSOptions(cert, key, ca_path=self.fleet_ca,
                          require_client_cert=True)

    async def _renew_certs_loop(self) -> None:
        """Re-enroll at 2/3 validity (reference: certify re-issues on
        demand). Outbound material rotates live; see SecurityConfig NOTE
        for the listener restart window."""
        from ..rpc.security import obtain_certificate
        sec = self.cfg.security
        while True:
            await asyncio.sleep(max(sec.cert_validity_s * 2 / 3, 60))
            try:
                token = sec.issue_token
                if not token and sec.issue_token_path:
                    # dflint: disable=DF001 — KB token reread at 2/3 cert validity (hours apart)
                    with open(sec.issue_token_path, encoding="utf-8") as f:
                        # dflint: disable=DF001 — see above: hours-apart renewal
                        token = f.read().strip()
                await obtain_certificate(
                    self.cfg.manager_addresses,
                    hosts=[self.host_ip, self.hostname], token=token,
                    out_dir=os.path.join(self.paths.cache_dir, "tls"),
                    validity_s=sec.cert_validity_s, tls_ca=sec.ca_cert)
                log.info("fleet certificate renewed")
            except Exception as exc:  # noqa: BLE001 - retry next cycle
                log.error("fleet certificate renewal failed: %s", exc)

    _active_in_process = 0   # daemons started but not yet stopped (this proc)

    async def start(self) -> None:
        # health plane FIRST: the watchdog must already be sweeping when
        # the earliest download section opens (refcounted process-wide,
        # like the metrics REGISTRY — co-resident daemons share it)
        from ..common import health
        self.health = health.PLANE
        self.health.acquire(self.cfg.health.to_plane())
        self.health.attach_recorder(self.flight_recorder)
        if self.cfg.plugin_dir:
            from ..common.plugins import load_source_plugins
            load_source_plugins(self.cfg.plugin_dir)
        if self.storage_mgr.reloaded_tasks:
            # warm restart: re-verify the reloaded pieces (crc32c, fanned
            # across the storage pool — never this loop) BEFORE anything
            # serves or advertises them; what fails verification is
            # dropped here, so the swarm only ever hears bytes that
            # re-hashed
            stats = await self.storage_mgr.verify_reloaded_async()
            log.info("warm restart: %d task(s) reloaded, %d piece(s) "
                     "verified, %d dropped", self.storage_mgr.reloaded_tasks,
                     stats.get("pieces_ok", 0),
                     stats.get("pieces_dropped", 0))
            if stats.get("pieces_rot", 0):
                # ROT only — pieces of COMPLETED tasks that once verified
                # and now hash wrong: the disk is lying, so self-
                # quarantine (stop advertising in PEX, flag every
                # announce) until an operator/restart re-verifies clean.
                # Pulling still works: quarantine is about not SERVING.
                # Drops from PARTIAL tasks are ordinary crash-torn writes
                # (data is not fsynced per write) and heal silently —
                # every unclean restart would otherwise sideline a
                # healthy daemon pod-wide.
                self.verdicts.self_quarantine(
                    f"boot re-verify found {stats['pieces_rot']} "
                    f"rotted piece(s) in completed tasks")
        if self.cfg.tracing.enabled:
            from ..common import tracing
            tracing.configure(
                service=f"dfdaemon/{self.hostname}",
                jsonl_path=self.cfg.tracing.jsonl_path or os.path.join(
                    self.paths.log_dir, "traces.jsonl"),
                otlp_endpoint=self.cfg.tracing.otlp_endpoint,
                sample_ratio=self.cfg.tracing.sample_ratio)
        # mTLS enrollment FIRST: the peer channel pool and the rpc server
        # both depend on the issued material
        self._rpc_tls = None
        self._peer_tls_ca = ""
        self._peer_tls_cert = ""
        self._peer_tls_key = ""
        if self.cfg.security.enabled:
            self._rpc_tls = await self._enroll_security()
        if self._peer_tls_cert:
            self.upload_server.tls = (self._peer_tls_cert,
                                      self._peer_tls_key, self._peer_tls_ca)
            # rollout knob applies to BOTH planes; must be set before
            # upload_server.start() decides whether to front a mux
            self.upload_server.tls_policy = self.cfg.security.tls_policy
        if self.cfg.download.source_ca or self.cfg.download.source_insecure:
            # the source client is a process singleton: remember the prior
            # trust setting so stop() restores it (co-resident daemons in
            # one process — the test suite — must not inherit this one's)
            from ..source.client import client_for
            http = client_for("https://")
            self._prev_source_tls = http._ssl
            http.set_tls(insecure=self.cfg.download.source_insecure,
                         ca_file=self.cfg.download.source_ca)
        await self.upload_server.start()
        self._peer_channels = ChannelPool(
            tls_ca=self._peer_tls_ca, tls_cert=self._peer_tls_cert,
            tls_key=self._peer_tls_key)
        tls_triple = ((self._peer_tls_cert, self._peer_tls_key,
                       self._peer_tls_ca)
                      if self._peer_tls_cert else None)
        self.upload_server.tls = tls_triple
        self._piece_downloader = PieceDownloader(
            timeout_s=self.cfg.download.piece_timeout_s, tls=tls_triple)
        engine_factory = self._p2p_engine_factory
        if engine_factory is None:
            def engine_factory() -> PieceEngine:
                return PieceEngine(
                    parallelism=self.cfg.download.piece_parallelism,
                    schedule_timeout_s=self.cfg.scheduler.schedule_timeout_s,
                    piece_timeout_s=self.cfg.download.piece_timeout_s,
                    downloader=self._piece_downloader,
                    channel_pool=self._peer_channels,
                    slice_name=(self.topology.slice_name
                                if self.topology else ""),
                    peer_observer=(self.pex.observe_parent
                                   if self.pex is not None else None),
                    relay=self.relay,
                    verdicts=self.verdicts)
        if self.pex is not None:
            # the pex rung builds a FRESH engine per pull (the scheduler
            # path may already have consumed the conductor's), and gossip
            # exchanges present the fleet client leaf under mTLS
            self.pex.engine_factory = engine_factory
            self.pex.tls = tls_triple
        self.shaper.start()
        self.ptm = PeerTaskManager(
            storage_mgr=self.storage_mgr, piece_mgr=self.piece_mgr,
            hostname=self.hostname, host_ip=self.host_ip,
            scheduler=None,
            p2p_engine_factory=engine_factory,
            device_sink_builder=self.device_sink_builder,
            is_seed=self.cfg.is_seed, shaper=self.shaper,
            prefetch_whole_file=self.cfg.download.prefetch_whole_file,
            flight_recorder=self.flight_recorder, pex=self.pex,
            relay=self.relay, qos=self.qos)
        svc = DaemonService(self.ptm,
                            upload_addr=f"{self.host_ip}:{self.upload_server.port}")
        # fleet mTLS: enroll with the manager, serve the peer RPC port with
        # the issued leaf, dial other peers trusting the fleet CA
        # peer-facing TCP server: bind the listen address, advertise host_ip
        self.rpc = RPCServer(f"{self.cfg.listen_ip}:{self.cfg.rpc_port}",
                             tls=self._rpc_tls,
                             tls_policy=self.cfg.security.tls_policy)
        for sdef in build_service(svc):
            self.rpc.register(sdef)
        await self.rpc.start()
        # scheduler connector needs the resolved rpc/upload ports for register
        if self._scheduler_factory is not None:
            self.scheduler = self._scheduler_factory(self)
        elif self.cfg.scheduler.addresses:
            self.scheduler = SchedulerConnector(
                self.cfg.scheduler.addresses, self.host_info(),
                register_timeout_s=self.cfg.scheduler.register_timeout_s,
                failover_n=self.cfg.scheduler.failover_n,
                demote_s=self.cfg.scheduler.demote_s)
        elif self.cfg.manager_addresses:
            await self._attach_manager()
        self.ptm.scheduler = self.scheduler
        # S2: demotion memory survives the daemon process (next to the
        # rest of the daemon's on-disk metadata) — covers every boot path
        # above (configured addresses, factory, manager discovery)
        await asyncio.to_thread(self._restore_scheduler_demotions)
        # local API over unix socket (dfget/dfcache/dfstore)
        sock = self.cfg.unix_sock or self.paths.daemon_sock()
        # dflint: disable=DF001 — stale-socket cleanup during start(), nothing is served yet
        if os.path.exists(sock):
            # dflint: disable=DF001 — see above: startup path
            os.unlink(sock)
        self.local_rpc = RPCServer(f"unix:{sock}")
        for sdef in build_service(svc):
            self.local_rpc.register(sdef)
        await self.local_rpc.start()
        self.unix_sock = sock
        # optional HTTP surfaces
        if self.cfg.proxy.enabled:
            from .proxy import ProxyServer
            self.proxy_server = ProxyServer(self, self.cfg.proxy)
            await self.proxy_server.start()
        if self.cfg.object_storage.enabled:
            from .objectstorage import ObjectGateway
            self.object_gateway = ObjectGateway(self, self.cfg.object_storage)
            await self.object_gateway.start()
        self.gc.add(GCTask("storage", self.cfg.storage.gc_interval_s,
                           self.storage_mgr.try_gc))
        self.gc.start()
        await self._wire_scheduler_extras()
        if self.pex is not None:
            self.pex.scheduler = self.scheduler
            # a warm-restarted daemon re-seeds its PEX digests from disk
            # NOW (one immediate push-pull round against bootstrap/known
            # peers) instead of after the first jittered interval — the
            # swarm learns the holder is back within one gossip round
            await self.pex.start(
                initial_round=bool(self.storage_mgr.reloaded_tasks))
        # counted only after everything above succeeded, consumed exactly
        # once by stop(): a failed start() or a double stop() must neither
        # strand the count high (leak fix disabled) nor drive it to zero
        # early (shared sessions yanked from a still-running daemon)
        self._counted_active = True
        Daemon._active_in_process += 1
        log.info("daemon up: host=%s ip=%s rpc=%s upload=%d sock=%s seed=%s",
                 self.hostname, self.host_ip, self.rpc.port,
                 self.upload_server.port, sock, self.cfg.is_seed)

    async def _attach_manager(self) -> None:
        """Discover schedulers via the manager (dynconfig role); seed
        daemons also register themselves as seed peers + keepalive."""
        from ..idl.messages import (GetSchedulersRequest,
                                    RegisterSeedPeerRequest)
        from ..rpc.manager_link import ManagerLink

        self.manager = ManagerLink(self.cfg.manager_addresses)
        try:
            if self.cfg.is_seed:
                await self.manager.register_seed_peer(RegisterSeedPeerRequest(
                    hostname=self.hostname, ip=self.host_ip,
                    port=self.rpc.port,
                    download_port=self.upload_server.port,
                    seed_peer_cluster_id=1, topology=self.topology))
                self.manager.start_keepalive(source_type="seed_peer",
                                             hostname=self.hostname,
                                             ip=self.host_ip,
                                             port=self.rpc.port)
            resp = await self.manager.get_schedulers(GetSchedulersRequest(
                hostname=self.hostname, ip=self.host_ip,
                topology=self.topology))
            addrs = [f"{s.ip}:{s.port}" for s in (resp.schedulers or [])]
            if addrs:
                self.scheduler = SchedulerConnector(
                    addrs, self.host_info(),
                    register_timeout_s=self.cfg.scheduler.register_timeout_s,
                    failover_n=self.cfg.scheduler.failover_n,
                    demote_s=self.cfg.scheduler.demote_s)
            else:
                log.info("manager knows no active schedulers; back-source "
                         "only until the refresh loop finds one")
        except Exception as exc:  # noqa: BLE001 - manager optional
            log.warning("manager attach failed (%s); back-source only", exc)
        if self.cfg.scheduler.refresh_interval_s > 0:
            self._sched_refresh = asyncio.get_running_loop().create_task(
                self._scheduler_refresh_loop())

    async def _wire_scheduler_extras(self) -> None:
        """Announcer + topology prober ride the scheduler connection; wired
        at boot AND when the refresh loop adopts a late scheduler — a
        healed daemon must announce itself and probe like one that booted
        after the scheduler."""
        if self.scheduler is None:
            return
        if self.pex is not None:
            # a late-adopted scheduler must also get the ticker's demoted-
            # member revival probe
            self.pex.scheduler = self.scheduler
        if self.announcer is None and hasattr(self.scheduler,
                                              "announce_host"):
            from .announcer import Announcer
            self.announcer = Announcer(self)
            await self.announcer.start()
        if (self.prober is None and self.cfg.probe_enabled
                and hasattr(self.scheduler, "sync_probes")):
            from .networktopology import NetworkTopologyProber
            self.prober = NetworkTopologyProber(self)
            await self.prober.start()

    async def _scheduler_refresh_loop(self) -> None:
        """Track the manager's scheduler set (reference daemon dynconfig
        refresh): a replaced scheduler reaches the ring, and a daemon that
        booted before ANY scheduler registered heals out of back-source-
        only the moment one appears. An empty/failed fetch keeps the last
        known set — a manager blip must not strand live schedulers."""
        from ..idl.messages import GetSchedulersRequest

        while True:
            await asyncio.sleep(self.cfg.scheduler.refresh_interval_s)
            try:
                resp = await self.manager.get_schedulers(GetSchedulersRequest(
                    hostname=self.hostname, ip=self.host_ip,
                    topology=self.topology))
                addrs = [f"{s.ip}:{s.port}"
                         for s in (resp.schedulers or [])]
                if not addrs:
                    continue
                if self.scheduler is None:
                    self.scheduler = SchedulerConnector(
                        addrs, self.host_info(),
                        register_timeout_s=self.cfg.scheduler
                        .register_timeout_s,
                        failover_n=self.cfg.scheduler.failover_n,
                        demote_s=self.cfg.scheduler.demote_s)
                    if self.ptm is not None:
                        self.ptm.scheduler = self.scheduler
                    await asyncio.to_thread(
                        self._restore_scheduler_demotions)
                    await self._wire_scheduler_extras()
                    log.info("schedulers appeared: %s", addrs)
                elif set(addrs) != set(self.scheduler.addresses):
                    log.info("scheduler set changed: %s -> %s",
                             self.scheduler.addresses, addrs)
                    self.scheduler.update_addresses(addrs)
            except Exception as exc:  # noqa: BLE001 - manager flaky is fine
                log.debug("scheduler refresh failed: %s", exc)

    def _demotions_path(self) -> str:
        return os.path.join(self.paths.data_dir, "scheduler_demotions.json")

    def _restore_scheduler_demotions(self) -> None:
        """S2: re-arm the connector's sticky demotion memory from the
        previous process — a restarted daemon must not re-probe every
        known-dead scheduler through the full register-timeout ladder."""
        if self.scheduler is None or not hasattr(self.scheduler,
                                                 "restore_demotions"):
            return
        try:
            with open(self._demotions_path(), "rb") as f:
                state = json.loads(f.read())
        except FileNotFoundError:
            return
        except (OSError, ValueError) as exc:
            log.debug("demotion state unreadable (%s); starting clean", exc)
            return
        self.scheduler.restore_demotions(state)

    def _persist_scheduler_demotions(self) -> None:
        """Counterpart of ``_restore_scheduler_demotions`` on the stop
        path (tmp+fsync+rename, the TaskMetadata.save idiom). Best
        effort: shutdown must not fail on a full disk."""
        if self.scheduler is None or not hasattr(self.scheduler,
                                                 "export_demotions"):
            return
        path = self._demotions_path()
        tmp = path + ".tmp"
        try:
            payload = json.dumps(self.scheduler.export_demotions(),
                                 sort_keys=True).encode()
            f = open(tmp, "wb")
            try:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            finally:
                f.close()          # fd released even on a torn write
            os.replace(tmp, path)
        except OSError as exc:
            log.debug("demotion persist failed: %s", exc)

    async def stop(self) -> None:
        renewal = getattr(self, "_cert_renewal", None)
        if renewal is not None:
            renewal.cancel()
        refresh = getattr(self, "_sched_refresh", None)
        if refresh is not None:
            refresh.cancel()
        if self.cfg.tracing.enabled:
            from ..common import tracing
            tracing.TRACER.flush()
        if hasattr(self, "_prev_source_tls"):
            from ..source.client import client_for
            client_for("https://")._ssl = self._prev_source_tls
            del self._prev_source_tls
        if getattr(self, "manager", None) is not None:
            await self.manager.close()
        if getattr(self, "prober", None) is not None:
            await self.prober.stop()
        await self.shaper.stop()
        if self.pex is not None:
            await self.pex.stop()
        if self.announcer is not None:
            await self.announcer.stop()
        await self.gc.stop()
        if self.ptm is not None:
            await self.ptm.shutdown()
        if self.proxy_server is not None:
            await self.proxy_server.stop()
        if self.object_gateway is not None:
            await self.object_gateway.stop()
        if self.local_rpc is not None:
            await self.local_rpc.stop(0.2)
        if self.rpc is not None:
            await self.rpc.stop(0.2)
        await self.upload_server.stop()
        if getattr(self, "_piece_downloader", None) is not None:
            await self._piece_downloader.close()
        if getattr(self, "_peer_channels", None) is not None:
            await self._peer_channels.close()
        if self.scheduler is not None:
            await asyncio.to_thread(self._persist_scheduler_demotions)
            if hasattr(self.scheduler, "leave_host"):
                await self.scheduler.leave_host()
            if hasattr(self.scheduler, "close"):
                await self.scheduler.close()
        # source-client sessions are process singletons shared by every
        # co-resident daemon: close them only when the LAST daemon leaves,
        # or asyncio reports them leaked on loop close (bench tpu phase)
        if getattr(self, "_counted_active", False):
            self._counted_active = False
            Daemon._active_in_process -= 1
            if Daemon._active_in_process == 0:
                from ..source.client import close_clients
                await close_clients()
        if getattr(self, "health", None) is not None:
            self.health.release()
            self.health = None
