"""The peer daemon (data plane): peertask engine, piece manager, storage,
upload server, proxy, object gateway, announcer — one per host.

Role parity: reference ``client/daemon`` (SURVEY §2.3)."""
