"""Daemon announcer: periodic host heartbeat to the scheduler.

Role parity: reference ``client/daemon/announcer/announcer.go`` — announce
host spec (CPU/mem/disk/net via gopsutil there; /proc + shutil here) to the
scheduler's ``AnnounceHost`` on an interval so the evaluator's free-slot and
load scores track reality.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil

from ..idl.messages import (AnnounceHostRequest, CPUStat, DiskStat, Host,
                            MemoryStat)

log = logging.getLogger("df.flow.announcer")


def _memory() -> MemoryStat:
    total = available = 0
    try:
        # dflint: disable=DF001 — tiny /proc/meminfo read on the announce interval; an executor hop costs more than the read
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1]) * 1024
    except OSError:
        pass
    used_pct = 100.0 * (1 - available / total) if total else 0.0
    return MemoryStat(total=total, available=available, used_percent=used_pct)


def _cpu() -> CPUStat:
    n = os.cpu_count() or 1
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        load1 = 0.0
    return CPUStat(logical_count=n, percent=min(100.0, 100.0 * load1 / n))


def _disk(path: str) -> DiskStat:
    try:
        # dflint: disable=DF001 — one statvfs on the announce interval, µs-scale
        du = shutil.disk_usage(path)
        return DiskStat(total=du.total, free=du.free,
                        used_percent=100.0 * du.used / du.total)
    except OSError:
        return DiskStat()


class Announcer:
    def __init__(self, daemon):
        self.daemon = daemon
        self.interval_s = daemon.cfg.announce_interval_s
        self._task: asyncio.Task | None = None

    def host_with_stats(self) -> Host:
        host = self.daemon.host_info()
        host.cpu = _cpu()
        host.memory = _memory()
        host.disk = _disk(self.daemon.paths.data_dir)
        return host

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.daemon.scheduler.announce_host(AnnounceHostRequest(
                    host=self.host_with_stats(), interval_s=self.interval_s))
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - scheduler may be away
                log.debug("announce failed: %s", exc)
            await asyncio.sleep(self.interval_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
