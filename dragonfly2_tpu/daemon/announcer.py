"""Daemon announcer: periodic host heartbeat + recovery content replay.

Role parity: reference ``client/daemon/announcer/announcer.go`` — announce
host spec (CPU/mem/disk/net via gopsutil there; /proc + shutil here) to the
scheduler's ``AnnounceHost`` on an interval so the evaluator's free-slot and
load scores track reality.

Beyond the reference: the announce loop is also the daemon's half of
control-plane crash recovery (scheduler/statestore.py). Every announce
response carries the scheduler's boot epoch; when the connector sees it
CHANGE — or a register fails over around the ring — the loop wakes
immediately and replays what this daemon holds (``AnnounceContent``, the
PEX digest entry shape sealed with the PEX envelope codec), so a restarted
brain relearns who holds what within one announce interval instead of
ruling the herd back to origin.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil

from ..idl.messages import (AnnounceContentRequest, AnnounceHostRequest,
                            CPUStat, DiskStat, Host, MemoryStat)
from .pulse import build_pulse

log = logging.getLogger("df.flow.announcer")


def _memory() -> MemoryStat:
    total = available = 0
    try:
        # dflint: disable=DF001 — tiny /proc/meminfo read on the announce interval; an executor hop costs more than the read
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1]) * 1024
    except OSError:
        pass
    used_pct = 100.0 * (1 - available / total) if total else 0.0
    return MemoryStat(total=total, available=available, used_percent=used_pct)


def _cpu() -> CPUStat:
    n = os.cpu_count() or 1
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        load1 = 0.0
    return CPUStat(logical_count=n, percent=min(100.0, 100.0 * load1 / n))


def _disk(path: str) -> DiskStat:
    try:
        # dflint: disable=DF001 — one statvfs on the announce interval, µs-scale
        du = shutil.disk_usage(path)
        return DiskStat(total=du.total, free=du.free,
                        used_percent=100.0 * du.used / du.total)
    except OSError:
        return DiskStat()


class Announcer:
    def __init__(self, daemon):
        self.daemon = daemon
        self.interval_s = daemon.cfg.announce_interval_s
        self._task: asyncio.Task | None = None
        # pulse sequence: lets the scheduler order digests and spot a
        # restart (seq reset) independently of wall clocks
        self._pulse_seq = 0

    def _pulse(self):
        """Build this announce's pulse digest; a pulse failure must never
        cost the heartbeat it rides on."""
        self._pulse_seq += 1
        try:
            return build_pulse(self.daemon, self._pulse_seq)
        except Exception:  # noqa: BLE001 - telemetry is best-effort
            return None

    def host_with_stats(self) -> Host:
        host = self.daemon.host_info()
        host.cpu = _cpu()
        host.memory = _memory()
        host.disk = _disk(self.daemon.paths.data_dir)
        return host

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    def _held_content(self) -> list[dict]:
        """PEX digest entry shape + ``url`` (the scheduler needs it to
        re-create the task record). A self-quarantined daemon advertises
        NOTHING — replaying a poisoner's inventory at a freshly recovered
        brain would be the exact re-offer the quarantine ladder exists to
        prevent."""
        verdicts = getattr(self.daemon, "verdicts", None)
        if verdicts is not None and verdicts.self_quarantined:
            return []
        entries = []
        for ts in self.daemon.storage_mgr.tasks():
            md = ts.md
            if not md.pieces and not (md.done and md.success):
                continue
            done = bool(md.done and md.success)
            entry = {"task_id": md.task_id, "url": md.url,
                     "total": md.total_piece_count,
                     "content_length": md.content_length,
                     "piece_size": md.piece_size, "done": done}
            if not done:
                entry["pieces"] = sorted(md.pieces)
            entries.append(entry)
        return entries

    async def _announce_content(self) -> None:
        from .pex import DIGEST_VERSION, seal
        entries = self._held_content()
        if not entries:
            return
        resp = await self.daemon.scheduler.announce_content(
            AnnounceContentRequest(
                host=self.host_with_stats(), pulse=self._pulse(),
                digest=seal({"v": DIGEST_VERSION, "tasks": entries})))
        log.info("re-announced %d held tasks (%d adopted)", len(entries),
                 getattr(resp, "tasks_adopted", 0))

    async def _loop(self) -> None:
        # initial replay: a daemon restarting over persisted storage
        # tells the brain what it still holds (the reverse direction of
        # scheduler recovery — same RPC, same codec)
        reconcile = True
        while True:
            try:
                await self.daemon.scheduler.announce_host(AnnounceHostRequest(
                    host=self.host_with_stats(), interval_s=self.interval_s,
                    pulse=self._pulse()))
                # announce_host fed the epoch watermark; a change (or a
                # register ring failover) left reconcile_event set
                event = getattr(self.daemon.scheduler, "reconcile_event",
                                None)
                if reconcile or (event is not None and event.is_set()):
                    if event is not None:
                        event.clear()
                    await self._announce_content()
                reconcile = False
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - scheduler may be away
                log.debug("announce failed: %s", exc)
            event = getattr(self.daemon.scheduler, "reconcile_event", None)
            if event is None:
                await asyncio.sleep(self.interval_s)
                continue
            # sleep the interval, but wake EARLY when the connector flags
            # a reconcile (epoch change / ring failover): the recovered
            # brain's first rulings are exactly when amnesia costs origin
            try:
                await asyncio.wait_for(event.wait(), self.interval_s)
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
