"""RPC transport: real gRPC (HTTP/2) with the msgpack IDL codec.

Role parity: reference ``pkg/rpc`` — client wrappers with retry/backoff,
server listen helpers, health service — plus ``pkg/balancer``'s
consistent-hashing scheduler picker. Services are registered as generic
method tables (no codegen); every method moves ``idl`` messages.
"""

from .server import RPCServer, ServiceDef, rpc_error_interceptor  # noqa: F401
from .client import Channel, ServiceClient, RPCError  # noqa: F401
from .balancer import HashRing, ConsistentHashPool  # noqa: F401
