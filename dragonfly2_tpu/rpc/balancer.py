"""Consistent-hash balancing across scheduler instances.

Role parity: reference ``pkg/balancer/consistent_hashing.go`` +
``pkg/resolver`` — every daemon hashes the task id onto the scheduler ring so
all peers of one task land on the same scheduler (scheduling state is
in-memory per scheduler). The pool is dynconfig-observable: address-set
changes rebuild the ring without dropping existing channels.
"""

from __future__ import annotations

import bisect
import hashlib
import logging
from typing import Sequence

from .client import Channel

log = logging.getLogger("df.rpc.balancer")


def _hash(key: str) -> int:
    # dflint: disable=DF001 — ring keys are "addr#vnode" strings, tens of bytes; the md5 is ns-scale
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    def __init__(self, nodes: Sequence[str] = (), replicas: int = 64):
        self.replicas = replicas
        self._ring: list[tuple[int, str]] = []
        self._nodes: set[str] = set()
        for n in nodes:
            self.add(n)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self.replicas):
            self._ring.append((_hash(f"{node}#{i}"), node))
        self._ring.sort()

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        self._ring = [(h, n) for h, n in self._ring if n != node]

    def nodes(self) -> set[str]:
        return set(self._nodes)

    def pick(self, key: str) -> str | None:
        if not self._ring:
            return None
        h = _hash(key)
        idx = bisect.bisect(self._ring, (h, ""))
        if idx == len(self._ring):
            idx = 0
        return self._ring[idx][1]

    def pick_n(self, key: str, n: int) -> list[str]:
        """The n distinct nodes clockwise from the key (failover order)."""
        if not self._ring:
            return []
        h = _hash(key)
        idx = bisect.bisect(self._ring, (h, ""))
        out: list[str] = []
        seen: set[str] = set()
        for i in range(len(self._ring)):
            _, node = self._ring[(idx + i) % len(self._ring)]
            if node not in seen:
                seen.add(node)
                out.append(node)
                if len(out) >= n:
                    break
        return out


class ConsistentHashPool:
    """Channels to a dynamic node set, picked by hashed key with failover."""

    def __init__(self, addresses: Sequence[str] = (), *, replicas: int = 64):
        self._ring = HashRing(addresses, replicas=replicas)
        self._channels: dict[str, Channel] = {}
        self._retired: list[Channel] = []  # removed but not yet closed
        self._close_tasks: set = set()     # strong refs so tasks aren't GC'd

    def update(self, addresses: Sequence[str]) -> None:
        want = set(addresses)
        for addr in want - self._ring.nodes():
            self._ring.add(addr)
        for addr in self._ring.nodes() - want:
            self._ring.remove(addr)
            ch = self._channels.pop(addr, None)
            if ch is not None:
                self._retired.append(ch)
        self._drain_retired()

    def _drain_retired(self) -> None:
        import asyncio
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # sync context: retired list drains on next update/close
        while self._retired:
            ch = self._retired.pop()
            t = loop.create_task(ch.close())
            self._close_tasks.add(t)
            t.add_done_callback(self._close_tasks.discard)

    def addresses(self) -> set[str]:
        return self._ring.nodes()

    def channel_for(self, key: str) -> Channel | None:
        addr = self._ring.pick(key)
        if addr is None:
            return None
        return self._channel(addr)

    def channels_for(self, key: str, n: int) -> list[Channel]:
        return [self._channel(a) for a in self._ring.pick_n(key, n)]

    def _channel(self, addr: str) -> Channel:
        ch = self._channels.get(addr)
        if ch is None:
            ch = Channel(addr)
            self._channels[addr] = ch
        return ch

    async def close(self) -> None:
        import asyncio
        for ch in list(self._channels.values()) + self._retired:
            await ch.close()
        self._channels.clear()
        self._retired.clear()
        if self._close_tasks:
            await asyncio.gather(*list(self._close_tasks), return_exceptions=True)
