"""gRPC server glue: register async handler tables, map DFError to status.

A service is a ``ServiceDef`` naming async handlers:

    svc = ServiceDef("df.scheduler.Scheduler")
    svc.unary_unary("RegisterPeerTask", handler)
    svc.stream_stream("ReportPieceResult", handler)

Handlers receive decoded ``idl`` messages (or async iterators of them) plus
the grpc context; DFError raised anywhere is carried to the peer in the
status message as ``DF:<code>:<text>`` and re-raised client-side.
"""

from __future__ import annotations

import logging
from typing import AsyncIterator, Awaitable, Callable

import grpc
import grpc.aio

from ..common.errors import Code, DFError
from ..idl import dumps, loads

log = logging.getLogger("df.rpc.server")

_KINDS = ("unary_unary", "unary_stream", "stream_unary", "stream_stream")


def _status_message(exc: BaseException) -> str:
    err = DFError.wrap(exc)
    return f"DF:{int(err.code)}:{err.message}"


class ServiceDef:
    def __init__(self, name: str):
        self.name = name
        self._methods: dict[str, grpc.RpcMethodHandler] = {}

    def _wrap_response_handler(self, fn):
        async def handler(request, context):
            try:
                return await fn(request, context)
            except DFError as exc:
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION, _status_message(exc))
            except grpc.aio.AbortError:
                raise
            except Exception as exc:  # noqa: BLE001 - boundary
                log.exception("handler %s failed", fn.__qualname__)
                await context.abort(grpc.StatusCode.INTERNAL, _status_message(exc))
        return handler

    def _wrap_stream_handler(self, fn):
        async def handler(request, context) -> AsyncIterator:
            try:
                async for resp in fn(request, context):
                    yield resp
            except DFError as exc:
                await context.abort(grpc.StatusCode.FAILED_PRECONDITION, _status_message(exc))
            except grpc.aio.AbortError:
                raise
            except Exception as exc:  # noqa: BLE001 - boundary
                log.exception("stream handler %s failed", fn.__qualname__)
                await context.abort(grpc.StatusCode.INTERNAL, _status_message(exc))
        return handler

    def unary_unary(self, method: str, fn: Callable[..., Awaitable]) -> None:
        self._methods[method] = grpc.unary_unary_rpc_method_handler(
            self._wrap_response_handler(fn),
            request_deserializer=loads, response_serializer=dumps)

    def unary_stream(self, method: str, fn: Callable[..., AsyncIterator]) -> None:
        self._methods[method] = grpc.unary_stream_rpc_method_handler(
            self._wrap_stream_handler(fn),
            request_deserializer=loads, response_serializer=dumps)

    def stream_unary(self, method: str, fn: Callable[..., Awaitable]) -> None:
        self._methods[method] = grpc.stream_unary_rpc_method_handler(
            self._wrap_response_handler(fn),
            request_deserializer=loads, response_serializer=dumps)

    def stream_stream(self, method: str, fn: Callable[..., AsyncIterator]) -> None:
        self._methods[method] = grpc.stream_stream_rpc_method_handler(
            self._wrap_stream_handler(fn),
            request_deserializer=loads, response_serializer=dumps)

    def build(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(self.name, self._methods)


def rpc_error_interceptor():  # placeholder hook point for tracing interceptors
    return None


def span_parent(context):
    """Extract the caller's W3C traceparent from gRPC invocation metadata
    (client half: rpc/client._trace_metadata). Returns a SpanContext to
    pass as ``tracing.span(..., parent=...)`` or None."""
    from ..common import tracing
    try:
        metadata = context.invocation_metadata() or ()
    except Exception:  # noqa: BLE001 - fake contexts in tests
        return None
    for entry in metadata:
        key, value = entry[0], entry[1]
        if key == "traceparent":
            return tracing.from_traceparent(value)
    return None


class _Health:
    """Minimal health service (role parity: ``pkg/rpc/health``)."""

    def __init__(self) -> None:
        self.serving = True

    async def check(self, request, context):
        from ..idl.messages import Empty
        if not self.serving:
            raise DFError(Code.UNAVAILABLE, "not serving")
        return Empty()


class TLSOptions:
    """Server-side TLS (reference ``pkg/rpc/mux.go`` credentials +
    ``security.go`` policies). ``ca_path`` set + ``require_client_cert``
    gives mTLS with manager-issued certs (``pkg/issuer``)."""

    def __init__(self, cert_path: str, key_path: str, *, ca_path: str = "",
                 require_client_cert: bool = False):
        self.cert_path = cert_path
        self.key_path = key_path
        self.ca_path = ca_path
        self.require_client_cert = require_client_cert

    def server_credentials(self) -> grpc.ServerCredentials:
        with open(self.key_path, "rb") as f:
            key = f.read()
        with open(self.cert_path, "rb") as f:
            cert = f.read()
        roots = None
        if self.ca_path:
            with open(self.ca_path, "rb") as f:
                roots = f.read()
        return grpc.ssl_server_credentials(
            [(key, cert)], root_certificates=roots,
            require_client_auth=self.require_client_cert)


class RPCServer:
    """One gRPC server hosting many ServiceDefs on one address.

    ``address`` may be "ip:port", "unix:/path", or "ip:0" (ephemeral —
    resolved port available as ``.port`` after ``start``). ``tls`` secures
    the listener (TLSOptions above).

    ``tls_policy`` (only meaningful with ``tls``; reference
    ``pkg/rpc/mux.go`` + ``credential.go``):
      "force"   — TLS only on the port (the prior behavior; default)
      "default" — plaintext AND TLS accepted on ONE port (rollout mode)
      "prefer"  — both accepted; plaintext flagged deprecated in logs +
                  metrics. Flip ``.mux.policy`` to "force" at runtime to
                  retire plaintext for new connections without a restart.
    """

    def __init__(self, address: str, *, options: list | None = None,
                 tls: TLSOptions | None = None, tls_policy: str = "force"):
        self.address = address
        self.port: int | None = None
        self.health = _Health()
        self.tls = tls
        if tls is not None:
            from .mux import POLICIES
            if tls_policy not in POLICIES:
                # fail BEFORE start() creates backend sockets — a typo'd
                # policy must not orphan a dfmux-* dir mid-startup
                raise ValueError(f"unknown tls_policy {tls_policy!r}")
        self.tls_policy = tls_policy
        self.mux = None                     # MuxListener when muxing
        self._server = grpc.aio.server(options=options or [
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            # grpc defaults SO_REUSEPORT on: two servers handed the same
            # port RANGE would both silently bind its first port and the
            # kernel would load-balance RPCs between the wrong processes —
            # binds must fail loudly so the range scan advances
            ("grpc.so_reuseport", 0),
        ])
        health_def = ServiceDef("df.health.Health")
        health_def.unary_unary("Check", self.health.check)
        self._defs: list[ServiceDef] = [health_def]

    def register(self, service: ServiceDef) -> None:
        self._defs.append(service)

    async def start(self) -> None:
        self._server.add_generic_rpc_handlers(tuple(d.build() for d in self._defs))
        muxing = (self.tls is not None and self.tls_policy != "force"
                  and not self.address.startswith("unix:"))
        if muxing:
            # both credentials on ONE public port: grpc-python cannot share
            # a listener between credential sets, so the mux front peeks
            # each connection and splices it to the matching unix-socket
            # backend (0700 dir — a loopback TCP backend would let on-host
            # processes bypass the policy and client-cert check; rpc/mux.py)
            from .mux import MuxListener
            plain_sock, tls_sock = MuxListener.backend_sockets()
            self._server.add_insecure_port(f"unix:{plain_sock}")
            self._server.add_secure_port(f"unix:{tls_sock}",
                                         self.tls.server_credentials())
            from .listen import bind_port_in_range, parse_port_spec
            ip, _, port_s = self.address.rpartition(":")
            lo, hi = parse_port_spec(port_s or "0")
            front_sock = None
            if hi > lo:
                # port-range spec: bind here (race-free) and hand the
                # bound socket to the mux front
                front_sock = bind_port_in_range(ip or "127.0.0.1", lo, hi)
            self.mux = MuxListener(ip or "127.0.0.1", lo,
                                   plain_sock=plain_sock, tls_sock=tls_sock,
                                   policy=self.tls_policy, sock=front_sock)
        elif self.tls is not None:
            port = self._add_port_ranged(
                lambda addr: self._server.add_secure_port(
                    addr, self.tls.server_credentials()))
        else:
            port = self._add_port_ranged(self._server.add_insecure_port)
        await self._server.start()
        if self.mux is not None:
            await self.mux.start()
            self.port = self.mux.port
        elif not self.address.startswith("unix:"):
            self.port = port
        log.info("rpc server on %s (port=%s, tls=%s, policy=%s): %s",
                 self.address, self.port, self.tls is not None,
                 self.tls_policy if self.tls is not None else "-",
                 ",".join(d.name for d in self._defs))

    def _add_port_ranged(self, add_port) -> int:
        """Bind ``address``, supporting an "ip:START-END" port range
        (reference ``pkg/rpc/server_listen.go`` ListenWithPortRange): the
        first port grpc can bind wins. grpc-python cannot adopt a pre-bound
        socket, so the probe IS the bind — no steal window."""
        if self.address.startswith("unix:") or "-" not in \
                self.address.rsplit(":", 1)[-1]:
            return add_port(self.address)
        from .listen import parse_port_spec
        ip, _, spec = self.address.rpartition(":")
        lo, hi = parse_port_spec(spec)
        for p in range(lo, hi + 1):
            try:
                port = add_port(f"{ip}:{p}")
            except RuntimeError:
                continue   # grpc >= 1.60 raises on a taken port
            if port:
                return port
        raise OSError(f"no free port in {self.address}")

    async def stop(self, grace: float = 1.0) -> None:
        if self.mux is not None:
            await self.mux.stop()
        await self._server.stop(grace)
        if self.mux is not None:
            self.mux.cleanup_backend_files()
