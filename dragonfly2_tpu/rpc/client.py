"""gRPC client helpers: typed service clients with retry/backoff, DFError
reconstruction, and stream call support.

Role parity: reference ``pkg/rpc/*/client`` wrappers (retry/backoff
interceptors, ``client_v1.go:126``-style method surface).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator

import grpc
import grpc.aio

from ..common import faultgate
from ..common.errors import Code, DFError
from ..common.retry import Retrier, RetryPolicy
from ..idl import dumps, loads

log = logging.getLogger("df.rpc.client")

_RETRYABLE = (grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.DEADLINE_EXCEEDED)
_RETRYABLE_DF = (Code.UNAVAILABLE, Code.DEADLINE_EXCEEDED)


def _transient_rpc(exc: BaseException) -> bool:
    """Unary retry classifier: transient transport failures, and injected
    faultgate DFErrors with the same codes (so the fault plane exercises
    the exact retry ladder real traffic takes)."""
    if isinstance(exc, grpc.aio.AioRpcError):
        return exc.code() in _RETRYABLE
    if isinstance(exc, DFError):
        return exc.code in _RETRYABLE_DF
    return False


def _trace_metadata():
    """W3C traceparent as gRPC metadata when a span is current (same
    contract as the piece HTTP path): one trace id then covers the
    daemon's task span, the scheduler's decision, and the piece fetches.
    Free when tracing is off — no current span means no metadata."""
    from ..common import tracing
    tp = tracing.traceparent()
    return (("traceparent", tp),) if tp else None


class RPCError(Exception):
    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(f"{code.name}: {message}")
        self.code = code
        self.message = message


def _translate(exc: grpc.aio.AioRpcError) -> Exception:
    """Rebuild DFError from the DF:<code>:<msg> status convention."""
    details = exc.details() or ""
    if details.startswith("DF:"):
        try:
            _, code_s, msg = details.split(":", 2)
            return DFError(Code(int(code_s)), msg)
        except (ValueError, KeyError):
            pass
    return RPCError(exc.code(), details)


class Channel:
    """A channel to one address, with lazily-created method stubs.

    Plaintext by default; ``tls_ca`` switches to TLS (trusting that CA —
    typically the manager's issuing CA), and ``tls_cert``/``tls_key`` adds
    the client certificate for mTLS servers (reference ``pkg/rpc/mux.go``
    client credentials)."""

    def __init__(self, address: str, *, options: list | None = None,
                 tls_ca: str = "", tls_cert: str = "", tls_key: str = "",
                 tls_server_name: str = ""):
        self.address = address
        opts = options or [
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
        ]
        if tls_ca or tls_cert:
            def _read(path: str) -> bytes | None:
                if not path:
                    return None
                with open(path, "rb") as f:
                    return f.read()
            creds = grpc.ssl_channel_credentials(
                root_certificates=_read(tls_ca),
                private_key=_read(tls_key), certificate_chain=_read(tls_cert))
            if tls_server_name:
                opts = [*opts, ("grpc.ssl_target_name_override",
                                tls_server_name)]
            self._channel = grpc.aio.secure_channel(address, creds,
                                                    options=opts)
        else:
            self._channel = grpc.aio.insecure_channel(address, options=opts)
        self._stubs: dict[tuple[str, str, str], Any] = {}

    def _stub(self, kind: str, service: str, method: str):
        key = (kind, service, method)
        stub = self._stubs.get(key)
        if stub is None:
            factory = getattr(self._channel, kind)
            stub = factory(f"/{service}/{method}",
                           request_serializer=dumps, response_deserializer=loads)
            self._stubs[key] = stub
        return stub

    async def close(self) -> None:
        await self._channel.close()

    async def wait_ready(self, timeout: float = 5.0) -> None:
        await asyncio.wait_for(self._channel.channel_ready(), timeout)


class ChannelPool:
    """LRU cache of channels keyed by address.

    Peers come and go; without eviction a long-lived daemon accumulates one
    open channel per parent ever dialed. ``limit`` bounds that: least
    recently used channels are closed as new addresses arrive.
    """

    def __init__(self, limit: int = 128, evict_grace_s: float = 120.0,
                 tls_ca: str = "", tls_cert: str = "", tls_key: str = ""):
        self.limit = limit
        self.evict_grace_s = evict_grace_s
        # fleet mTLS: verify peers against the CA AND present our leaf
        self.tls_ca = tls_ca
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._channels: dict[str, Channel] = {}
        self._evicted: list[Channel] = []
        self._closers: set[asyncio.Task] = set()

    def get(self, address: str) -> Channel:
        ch = self._channels.pop(address, None)
        if ch is None:
            ch = Channel(address, tls_ca=self.tls_ca,
                         tls_cert=self.tls_cert, tls_key=self.tls_key)
            while len(self._channels) >= self.limit:
                oldest = next(iter(self._channels))
                self._evict(self._channels.pop(oldest))
        self._channels[address] = ch   # re-insert = most recently used
        return ch

    def _evict(self, ch: Channel) -> None:
        # grace-period close: streams opened on this channel (piece sync
        # bidis) get time to finish before the channel dies under them
        self._evicted.append(ch)

        async def delayed() -> None:
            await asyncio.sleep(self.evict_grace_s)
            try:
                self._evicted.remove(ch)
            except ValueError:
                return            # pool.close() beat us to it
            await ch.close()

        t = asyncio.get_running_loop().create_task(delayed())
        self._closers.add(t)
        t.add_done_callback(self._closers.discard)

    async def close(self) -> None:
        for t in list(self._closers):
            t.cancel()
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
        for ch in self._evicted:
            await ch.close()
        self._evicted.clear()


class ServiceClient:
    """Typed calls against one service on one channel."""

    def __init__(self, channel: Channel, service: str, *,
                 max_attempts: int = 3, base_backoff: float = 0.1,
                 max_backoff: float = 2.0):
        self.channel = channel
        self.service = service
        self.max_attempts = max_attempts
        self.base_backoff = base_backoff
        self.max_backoff = max_backoff
        self.retry_policy = RetryPolicy(max_attempts=max_attempts,
                                        base_s=base_backoff,
                                        max_s=max_backoff)

    async def unary(self, method: str, request: Any, *, timeout: float | None = None) -> Any:
        md = _trace_metadata()
        stub = self.channel._stub("unary_unary", self.service, method)
        gate_key = f"{self.channel.address}/{self.service}/{method}"

        async def call():
            if faultgate.ARMED:
                # the per-call deadline must bound the injected fault too:
                # the grpc timeout below only covers the stub, so a 'hang'
                # script fired before it would otherwise park for an hour
                if timeout:
                    try:
                        await asyncio.wait_for(
                            faultgate.fire("rpc.unary", key=gate_key),
                            timeout)
                    except asyncio.TimeoutError:
                        raise DFError(Code.DEADLINE_EXCEEDED,
                                      f"{gate_key}: deadline during "
                                      "injected fault") from None
                else:
                    await faultgate.fire("rpc.unary", key=gate_key)
            return await stub(request, timeout=timeout, metadata=md)

        def on_retry(failures, exc, pause):
            log.debug("retrying %s/%s after %s (%.2fs)",
                      self.service, method, exc, pause)

        try:
            return await Retrier(self.retry_policy).run(
                call, retryable=_transient_rpc, on_retry=on_retry)
        except grpc.aio.AioRpcError as exc:
            raise _translate(exc) from None

    def unary_stream(self, method: str, request: Any, *,
                     timeout: float | None = None) -> "_StreamIter":
        stub = self.channel._stub("unary_stream", self.service, method)
        return _StreamIter(stub(request, timeout=timeout,
                                metadata=_trace_metadata()))

    async def stream_unary(self, method: str, requests: AsyncIterator[Any], *,
                           timeout: float | None = None) -> Any:
        stub = self.channel._stub("stream_unary", self.service, method)
        try:
            return await stub(requests, timeout=timeout,
                              metadata=_trace_metadata())
        except grpc.aio.AioRpcError as exc:
            raise _translate(exc) from None

    def stream_stream(self, method: str, *, timeout: float | None = None) -> "_BidiCall":
        stub = self.channel._stub("stream_stream", self.service, method)
        return _BidiCall(stub(timeout=timeout, metadata=_trace_metadata()))


class _StreamIter:
    """Server-stream iterator translating grpc errors to DFError/RPCError."""

    def __init__(self, call):
        self.call = call

    def cancel(self) -> None:
        self.call.cancel()

    def __aiter__(self):
        return self

    async def __anext__(self):
        msg = await self.read()
        if msg is None:
            raise StopAsyncIteration
        return msg

    async def read(self):
        """Like __anext__ but returns None at end of stream."""
        try:
            if faultgate.ARMED:
                await faultgate.fire("rpc.stream.read")
            msg = await self.call.read()
        except grpc.aio.AioRpcError as exc:
            raise _translate(exc) from None
        if msg is grpc.aio.EOF:
            return None
        return msg


class _BidiCall:
    """Bidirectional stream with explicit write/read halves."""

    def __init__(self, call):
        self.call = call

    async def write(self, msg: Any) -> None:
        try:
            await self.call.write(msg)
        except grpc.aio.AioRpcError as exc:
            raise _translate(exc) from None

    async def done_writing(self) -> None:
        await self.call.done_writing()

    async def read(self) -> Any | None:
        try:
            if faultgate.ARMED:
                await faultgate.fire("rpc.stream.read")
            msg = await self.call.read()
        except grpc.aio.AioRpcError as exc:
            raise _translate(exc) from None
        if msg is grpc.aio.EOF:
            return None
        return msg

    def cancel(self) -> None:
        self.call.cancel()

    def __aiter__(self):
        return self

    async def __anext__(self):
        msg = await self.read()
        if msg is None:
            raise StopAsyncIteration
        return msg
