"""Fleet cert enrollment: obtain a manager-signed certificate.

Role parity: reference ``pkg/issuer`` + certify integration
(``client/daemon/daemon.go:367-458``) — the daemon generates a keypair
locally, submits the PUBLIC half to the manager's ``IssueCertificate``
(gated by the issuance token, ideally over the manager's TLS port), and
serves its own listeners with the returned leaf. Private keys never cross
the wire.
"""

from __future__ import annotations

import asyncio
import logging
import os

log = logging.getLogger("df.rpc.security")


async def obtain_certificate(manager_addresses: list[str], *,
                             hosts: list[str], token: str,
                             out_dir: str, validity_s: int = 24 * 3600,
                             tls_ca: str = "") -> tuple[str, str, str]:
    """Enroll with the first reachable manager; returns
    (cert_path, key_path, ca_path) written 0600 under ``out_dir``."""
    from ..common import cryptoshim
    # no-op when the real wheel is importable; first call may probe for
    # an openssl binary, so keep it off the loop thread
    await asyncio.to_thread(cryptoshim.install)
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ec

    from ..idl.messages import CertificateRequest
    from .client import Channel, ServiceClient

    key = ec.generate_private_key(ec.SECP256R1())
    pub_pem = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    last_exc: Exception | None = None
    for addr in manager_addresses:
        ch = Channel(addr, tls_ca=tls_ca)
        try:
            mc = ServiceClient(ch, "df.manager.Manager")
            resp = await mc.unary("IssueCertificate", CertificateRequest(
                public_key_pem=pub_pem, hosts=hosts, token=token,
                validity_s=validity_s), timeout=30.0)
            # dflint: disable=DF001 — enrollment materializes KB-scale PEMs once per cert validity window
            os.makedirs(out_dir, exist_ok=True)
            cert_path = os.path.join(out_dir, "peer.crt")
            key_path = os.path.join(out_dir, "peer.key")
            ca_path = os.path.join(out_dir, "fleet-ca.crt")
            # dflint: disable=DF001 — see above: rare KB-scale cert writes
            with open(cert_path, "wb") as f:
                # dflint: disable=DF001 — see above: rare KB-scale cert writes
                f.write(resp.cert_pem)
            fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                         0o600)
            with os.fdopen(fd, "wb") as f:
                # dflint: disable=DF001 — see above: rare KB-scale cert writes
                f.write(key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption()))
            # dflint: disable=DF001 — see above: rare KB-scale cert writes
            with open(ca_path, "wb") as f:
                # dflint: disable=DF001 — see above: rare KB-scale cert writes
                f.write(resp.ca_cert_pem)
            log.info("fleet certificate issued by %s for %s", addr, hosts)
            return cert_path, key_path, ca_path
        except Exception as exc:  # noqa: BLE001 - try next manager
            last_exc = exc
        finally:
            await ch.close()
    raise RuntimeError(f"certificate enrollment failed: {last_exc}")
