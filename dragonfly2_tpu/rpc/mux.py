"""TLS/plaintext mux: both protocols on ONE listen port, with a rollout
policy.

Role parity: reference ``pkg/rpc/mux.go`` (cmux splitting TLS from h2c on
one listener) + ``pkg/rpc/credential.go`` (default/prefer/force policies).
Without this, turning mTLS on across a fleet is a flag day: every peer's
client and server must flip together or half the mesh goes dark. With it,
servers accept both during the rollout and ``force`` retires plaintext —
for NEW connections only, so nothing in flight is dropped.

Design: the public port is a tiny asyncio front listener that peeks the
first byte of each connection — 0x16 is a TLS record's handshake type;
gRPC's h2c preface starts with 'P' (PRI * HTTP/2.0) — and splices bytes to
one of two backend listeners of the SAME grpc.aio server (grpc-python
cannot share one listener between credentials; the reference's Go cmux
hands off accepted conns in-process, ours costs one local hop). The
backends are UNIX SOCKETS in a 0700 directory, not loopback TCP: a
loopback port would let any on-host process skip the mux — and its policy
and the TLS client-cert check — entirely. A same-uid process can still
reach the sockets, but a same-uid process can also read the TLS keys, so
no boundary is weakened.
"""

from __future__ import annotations

import asyncio
import logging
import os
import tempfile

from ..common.metrics import REGISTRY

log = logging.getLogger("df.rpc.mux")

_conns = REGISTRY.counter("df_rpc_mux_conns_total",
                          "mux accepted connections", ("kind",))

POLICIES = ("default", "prefer", "force")
TLS_HANDSHAKE_BYTE = 0x16


class MuxListener:
    """Front listener splicing TLS vs plaintext to two backend sockets.

    ``policy`` is mutable at runtime (the rollout knob):
      default — serve both, no judgement
      prefer  — serve both; count + log plaintext as deprecated
      force   — refuse NEW plaintext connections (existing ones live on)
    """

    def __init__(self, listen_ip: str, port: int, *,
                 plain_sock: str, tls_sock: str,
                 policy: str = "default", sock=None):
        """``sock``: an already-BOUND listening socket the front should
        serve instead of binding listen_ip:port itself — how a port-RANGE
        spec (``rpc.listen.bind_port_in_range``) or an AF_VSOCK listener
        (``rpc.listen.vsock_listener``, VM-isolated deployments) fronts a
        grpc server that cannot bind those itself."""
        if policy not in POLICIES:
            raise ValueError(f"unknown mux policy {policy!r}")
        self.listen_ip = listen_ip
        self.port = port
        self.plain_sock = plain_sock
        self.tls_sock = tls_sock
        self.policy = policy
        self._sock = sock
        self._server: asyncio.Server | None = None
        self._warned_plain = False

    @staticmethod
    def backend_sockets() -> tuple[str, str]:
        """(plain, tls) unix socket paths in a fresh 0700 directory."""
        d = tempfile.mkdtemp(prefix="dfmux-")
        # dflint: disable=DF001 — one chmod on a fresh tempdir during server start, metadata syscall
        os.chmod(d, 0o700)
        return os.path.join(d, "plain.sock"), os.path.join(d, "tls.sock")

    async def start(self) -> None:
        if self._sock is not None:
            self._server = await asyncio.start_server(
                self._handle, sock=self._sock)
        else:
            self._server = await asyncio.start_server(
                self._handle, self.listen_ip, self.port)
        name = self._server.sockets[0].getsockname()
        self.port = name[1] if isinstance(name, tuple) and len(name) > 1 \
            else self.port
        log.info("mux on %s -> %s / %s (policy=%s)",
                 name, self.plain_sock, self.tls_sock, self.policy)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def cleanup_backend_files(self) -> None:
        """Best-effort removal of the backend unix sockets and their
        tempdir — call AFTER the backend servers have shut down, or every
        restart leaks one dfmux-* directory."""
        for path in (self.plain_sock, self.tls_sock):
            try:
                # dflint: disable=DF001 — socket unlink during server stop, metadata syscall
                os.unlink(path)
            except OSError:
                pass
        try:
            # dflint: disable=DF001 — tempdir rmdir during server stop, metadata syscall
            os.rmdir(os.path.dirname(self.plain_sock))
        except OSError:
            pass

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            first = await asyncio.wait_for(reader.read(1), timeout=30.0)
        except (asyncio.TimeoutError, ConnectionError):
            writer.close()
            return
        if not first:
            writer.close()
            return
        is_tls = first[0] == TLS_HANDSHAKE_BYTE
        if not is_tls:
            if self.policy == "force":
                _conns.labels("plain_refused").inc()
                log.warning("refusing plaintext connection (policy=force)")
                writer.close()
                try:
                    await writer.wait_closed()
                except OSError:
                    pass
                return
            if self.policy == "prefer" and not self._warned_plain:
                self._warned_plain = True
                log.warning("plaintext peer connected (policy=prefer): "
                            "schedule its TLS upgrade")
        _conns.labels("tls" if is_tls else "plain").inc()
        backend = self.tls_sock if is_tls else self.plain_sock
        try:
            up_r, up_w = await asyncio.open_unix_connection(backend)
        except OSError:
            writer.close()
            return
        up_w.write(first)

        async def pump(src: asyncio.StreamReader,
                       dst: asyncio.StreamWriter) -> None:
            try:
                while True:
                    data = await src.read(64 * 1024)
                    if not data:
                        break
                    dst.write(data)
                    await dst.drain()
            except (ConnectionError, OSError):
                pass
            finally:
                try:
                    dst.close()
                except OSError:
                    pass

        await asyncio.gather(pump(reader, up_w), pump(up_r, writer))
        for w in (writer, up_w):
            try:
                await w.wait_closed()
            except OSError:
                pass
