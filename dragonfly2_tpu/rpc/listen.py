"""Listener helpers: port-range and vsock listen.

Role parity: reference ``pkg/rpc/server_listen.go`` (``ListenWithPortRange``
— first free port in [start, end] wins, used where fleets pin service ports
to firewall-approved ranges) and ``pkg/rpc/vsock.go`` (AF_VSOCK listeners
for VM-isolated deployments, e.g. firecracker guests talking to a host
daemon without a NIC).

gRPC-python cannot bind AF_VSOCK itself; vsock deployments put the
``rpc.mux.MuxListener`` front (or any asyncio server) on the vsock and let
it splice to the server's unix-socket backends.
"""

from __future__ import annotations

import socket

VSOCK_CID_ANY = -1


def parse_port_spec(spec: str) -> tuple[int, int]:
    """"8000" -> (8000, 8000); "8000-8010" -> (8000, 8010); "0" -> (0, 0)."""
    start, _, end = spec.partition("-")
    lo = int(start)
    hi = int(end) if end else lo
    if hi < lo:
        raise ValueError(f"port range end < start: {spec!r}")
    return lo, hi


def bind_port_in_range(ip: str, start: int, end: int) -> socket.socket:
    """First bindable TCP port in [start, end] (reference
    ``ListenWithPortRange``); start == 0 binds ephemeral. Returns the BOUND
    listening socket — the mux front serves it directly
    (``MuxListener(sock=...)``, see RPCServer's muxing branch) so no other
    process can steal the port between probe and use. Plain grpc listeners
    instead scan the range with per-port binds
    (``RPCServer._add_port_ranged`` — grpc cannot adopt a bound socket)."""
    last_exc: OSError | None = None
    for port in range(start, end + 1):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((ip, port))
            s.listen(128)
            return s
        except OSError as exc:
            s.close()
            last_exc = exc
    raise OSError(f"no free port in {ip}:{start}-{end}") from last_exc


def vsock_listener(port: int, cid: int = VSOCK_CID_ANY) -> socket.socket:
    """Bound AF_VSOCK listening socket (reference ``pkg/rpc/vsock.go``).
    Raises OSError where the kernel lacks vsock support — callers surface
    that as a configuration error, not a silent TCP fallback."""
    if not hasattr(socket, "AF_VSOCK"):
        raise OSError("AF_VSOCK not supported on this platform")
    cid = socket.VMADDR_CID_ANY if cid == VSOCK_CID_ANY else cid
    s = socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)
    s.bind((cid, port))
    s.listen(128)
    return s
