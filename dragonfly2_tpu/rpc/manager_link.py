"""Client link to the manager: registration, discovery, keepalive.

Role parity: reference ``pkg/rpc/manager/client`` + the keepalive goroutines
in scheduler/seed-peer announcers. Shared by the scheduler (register self,
find seed peers) and the daemon (find schedulers; seed daemons register as
seed peers).
"""

from __future__ import annotations

import asyncio
import logging

from ..idl.messages import (GetSchedulersRequest, GetSchedulersResponse,
                            GetSeedPeersRequest, GetSeedPeersResponse,
                            KeepAliveRequest)
from .client import Channel, ServiceClient

log = logging.getLogger("df.rpc.mgrlink")

MANAGER_SERVICE = "df.manager.Manager"


class ManagerLink:
    def __init__(self, addresses: list[str], *,
                 keepalive_interval_s: float = 15.0):
        self.addresses = list(addresses)
        self.keepalive_interval_s = keepalive_interval_s
        self._channel: Channel | None = None
        self._addr_idx = 0
        self._keepalive_task: asyncio.Task | None = None

    def _client(self) -> ServiceClient:
        if self._channel is None:
            addr = self.addresses[self._addr_idx % len(self.addresses)]
            self._channel = Channel(addr)
        return ServiceClient(self._channel, MANAGER_SERVICE)

    async def _failover(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
        self._addr_idx += 1

    async def _unary(self, method: str, req, *, timeout: float = 10.0):
        """Try every configured manager address before giving up — an HA
        pair with a dead first address must not look globally down."""
        last: Exception | None = None
        for _ in range(max(1, len(self.addresses))):
            try:
                return await self._client().unary(method, req,
                                                  timeout=timeout)
            except Exception as exc:  # noqa: BLE001 - rotate and retry
                last = exc
                await self._failover()
        raise last  # type: ignore[misc]

    # -- calls ---------------------------------------------------------

    async def register_scheduler(self, req) -> None:
        await self._unary("RegisterScheduler", req)

    async def register_seed_peer(self, req) -> None:
        await self._unary("RegisterSeedPeer", req)

    async def get_schedulers(self, req: GetSchedulersRequest
                             ) -> GetSchedulersResponse:
        return await self._unary("GetSchedulers", req)

    async def get_seed_peers(self, cluster_id: int = 0) -> GetSeedPeersResponse:
        return await self._unary(
            "GetSeedPeers", GetSeedPeersRequest(cluster_id=cluster_id))

    async def list_applications(self):
        from ..idl.messages import Empty
        return await self._unary("ListApplications", Empty())

    async def list_tenants(self):
        from ..idl.messages import Empty
        return await self._unary("ListTenants", Empty())

    async def set_scheduler_state(self, req) -> None:
        """Park a scheduler's handoff blob (control-plane failover)."""
        await self._unary("SetSchedulerState", req)

    async def get_scheduler_state(self, req):
        return await self._unary("GetSchedulerState", req)

    async def create_model(self, req) -> None:
        await self._unary("CreateModel", req, timeout=60.0)

    async def get_model(self, req):
        return await self._unary("GetModel", req, timeout=60.0)

    # -- keepalive -----------------------------------------------------

    def start_keepalive(self, *, source_type: str, hostname: str, ip: str,
                        cluster_id: int = 0, port: int = 0) -> None:
        if self._keepalive_task is None:
            self._keepalive_task = asyncio.get_running_loop().create_task(
                self._keepalive_loop(source_type, hostname, ip, cluster_id,
                                     port))

    async def _keepalive_loop(self, source_type: str, hostname: str, ip: str,
                              cluster_id: int, port: int) -> None:
        while True:
            try:
                stream_started = asyncio.get_running_loop().time()

                async def beats():
                    while True:
                        yield KeepAliveRequest(source_type=source_type,
                                               hostname=hostname, ip=ip,
                                               cluster_id=cluster_id,
                                               port=port)
                        await asyncio.sleep(self.keepalive_interval_s)

                await self._client().stream_unary("KeepAlive", beats())
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - manager away; retry
                log.debug("keepalive stream error: %s", exc)
                # fast failure right after connect: rotate to the next address
                if (asyncio.get_running_loop().time() - stream_started
                        < self.keepalive_interval_s):
                    await self._failover()
            await asyncio.sleep(min(5.0, self.keepalive_interval_s))

    async def close(self) -> None:
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
            try:
                await self._keepalive_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        if self._channel is not None:
            await self._channel.close()
