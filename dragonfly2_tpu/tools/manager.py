"""Manager launcher: ``python -m dragonfly2_tpu.tools.manager``.

Role parity: reference ``cmd/manager`` (cobra launcher over
``manager.New``/``Serve``).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ..common import logging as dflog
from ..common.config import env_overrides, load_config
from ..manager.server import Manager, ManagerConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="df-manager")
    p.add_argument("--config", default="", help="YAML/JSON config file")
    p.add_argument("--grpc-port", type=int, default=0)
    p.add_argument("--rest-port", type=int, default=0)
    p.add_argument("--listen-ip", default="")
    p.add_argument("--db", default="", help="sqlite path ('' = in-memory)")
    p.add_argument("--workdir", default="")
    p.add_argument("--auth", action="store_true",
                   help="enable REST auth/RBAC (bootstraps a root user)")
    p.add_argument("--issue-certs", action="store_true",
                   help="enable fleet certificate issuance")
    from ..common.debug_http import add_debug_arg
    add_debug_arg(p)
    p.add_argument("--verbose", "-v", action="store_true")
    return p


async def serve(cfg: ManagerConfig, debug_port: int = 0) -> None:
    from ..common import health
    health.PLANE.acquire()   # loop watchdog + /debug/health on --debug-port
    mgr = Manager(cfg)
    await mgr.start()
    from ..common.debug_http import maybe_start_debug
    debug_runner = await maybe_start_debug(debug_port)
    print(f"manager up: grpc={mgr.address} rest=:{mgr.rest.port}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if debug_runner is not None:
        await debug_runner.cleanup()
    await mgr.stop()
    health.PLANE.release()
    from ..common import tracing
    # the OTLP drain sleeps in bounded 50 ms hops — off-loop, so a
    # still-draining RPC server isn't parked behind the span flush
    await asyncio.to_thread(tracing.shutdown)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    dflog.setup("DEBUG" if args.verbose else "INFO")
    overrides: dict = env_overrides()
    if args.grpc_port:
        overrides["grpc_port"] = args.grpc_port
    if args.rest_port:
        overrides["rest_port"] = args.rest_port
    if args.listen_ip:
        overrides["listen_ip"] = args.listen_ip
    if args.db:
        overrides["db_path"] = args.db
    if args.workdir:
        overrides["workdir"] = args.workdir
    if args.auth:
        overrides["auth_enabled"] = True
    if args.issue_certs:
        overrides["issue_certs"] = True
    cfg = load_config(ManagerConfig, args.config or None, overrides)
    asyncio.run(serve(cfg, debug_port=args.debug_port))
    return 0


if __name__ == "__main__":
    sys.exit(main())
