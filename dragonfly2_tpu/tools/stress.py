"""Stress tool: concurrent download load with a latency histogram.

Role parity: reference ``test/tools/stress/main.go`` — N workers hammer a
URL (directly or through the daemon proxy) for a duration, then report
request/error counts, throughput, and latency percentiles. One JSON line on
stdout so harnesses can parse it.

Usage:
    python -m dragonfly2_tpu.tools.stress --url http://origin/blob \
        [--proxy http://127.0.0.1:65001] [-c 16] [-d 10] \
        [--chaos 'piece.wire=delay:0.2:n=-1' \
         --chaos-target http://127.0.0.1:UPLOAD_PORT]

``--chaos`` arms a faultgate script (common/faultgate.py syntax; see
docs/RESILIENCE.md) for the duration of the run and disarms it after;
``--pod-report host1:port,host2:port`` attaches the podscope pod summary
(docs/OBSERVABILITY.md) so the report says what the POD did under load,
not just what this client saw; ``--ctrl-report sched_host:debug_port``
likewise attaches the scheduler's /debug/ctrl observatory snapshot
(rulings/sec, worst ruling phase, bytes of scheduler state).
With ``--chaos-target`` the script is POSTed to that daemon's
``/debug/faults`` surface (requires ``upload.debug_endpoints: true``), so
a LIVE daemon takes the faults while this tool measures what its clients
experience; without a target the script arms in this process only.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (the reference's
    histogram reports the same P50/P90/P95/P99 cut points)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def parse_class_mix(specs: list[str], concurrency: int) -> list[tuple]:
    """``--priority`` specs -> per-class worker allocation.

    Each spec is ``class`` or ``class:workers`` (class from the pinned
    PRIORITY_CLASSES vocabulary). With no spec every worker runs
    classless, the pre-QoS behavior. Workers left unallocated by
    explicit counts run as ``standard``."""
    from ..idl.messages import PRIORITY_CLASSES
    if not specs:
        return [("", concurrency)]
    out: list[tuple] = []
    used = 0
    for spec in specs:
        cls, _, n = spec.partition(":")
        if cls not in PRIORITY_CLASSES:
            raise SystemExit(
                f"stress: unknown class {cls!r} in --priority "
                f"(known: {list(PRIORITY_CLASSES)})")
        workers = int(n) if n else 1
        out.append((cls, workers))
        used += workers
    if used < concurrency:
        out.append(("standard", concurrency - used))
    return out


def _class_stats() -> dict:
    return {"requests": 0, "errors": 0, "shed": 0, "bytes": 0,
            "latencies": []}


async def run_stress(url: str, *, proxy: str = "", concurrency: int = 8,
                     duration_s: float = 10.0,
                     connect_timeout_s: float = 10.0,
                     tenant: str = "",
                     class_mix: list[tuple] | None = None) -> dict:
    import aiohttp

    deadline = time.monotonic() + duration_s
    mix = class_mix or [("", concurrency)]
    per_class: dict[str, dict] = {}

    async def worker(session: aiohttp.ClientSession, cls: str) -> None:
        stats = per_class.setdefault(cls or "", _class_stats())
        headers = {}
        if cls:
            headers["X-Dragonfly-Class"] = cls
        if tenant:
            headers["X-Dragonfly-Tenant"] = tenant
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            try:
                async with session.get(url, proxy=proxy or None,
                                       headers=headers or None) as resp:
                    got = 0
                    async for chunk in resp.content.iter_chunked(1 << 20):
                        got += len(chunk)
                    if resp.status == 429:
                        # the QoS shed path (brownout / tenant quota):
                        # counted apart from errors — a shed under
                        # contention is the plane working, and honoring
                        # Retry-After is what a well-behaved bulk
                        # client does
                        stats["shed"] += 1
                        retry = resp.headers.get("Retry-After", "")
                        pause = (float(retry) if retry.strip().isdigit()
                                 else 0.5)
                        await asyncio.sleep(min(pause, max(
                            deadline - time.monotonic(), 0.0)))
                    elif resp.status not in (200, 206):
                        stats["errors"] += 1
                    else:
                        stats["bytes"] += got
                        stats["latencies"].append(time.monotonic() - t0)
            except Exception:  # noqa: BLE001 - counted, load goes on
                stats["errors"] += 1
            stats["requests"] += 1

    # sock_read: a server that stalls mid-body (what a stress tool exists
    # to expose) must count as an error, not hang the run past its deadline
    timeout = aiohttp.ClientTimeout(total=None, sock_connect=connect_timeout_s,
                                    sock_read=max(duration_s, 10.0))
    async with aiohttp.ClientSession(timeout=timeout) as session:
        t0 = time.monotonic()
        workers = [worker(session, cls)
                   for cls, n in mix for _ in range(n)]
        await asyncio.gather(*workers)
        elapsed = time.monotonic() - t0

    latencies = sorted(lat for s in per_class.values()
                       for lat in s["latencies"])
    classes = {}
    for cls, s in per_class.items():
        lats = sorted(s.pop("latencies"))
        classes[cls or "unclassed"] = {
            **s,
            "latency_ms": {
                "p50": round(_percentile(lats, 0.50) * 1000, 1),
                "p99": round(_percentile(lats, 0.99) * 1000, 1),
            },
        }
    result = {
        "url": url,
        "concurrency": concurrency,
        "duration_s": round(elapsed, 2),
        "requests": sum(s["requests"] for s in classes.values()),
        "errors": sum(s["errors"] for s in classes.values()),
        "shed": sum(s["shed"] for s in classes.values()),
        "bytes": sum(s["bytes"] for s in classes.values()),
        "throughput_gbps": round(
            sum(s["bytes"] for s in classes.values()) / 1e9
            / max(elapsed, 1e-9), 4),
        "latency_ms": {
            "p50": round(_percentile(latencies, 0.50) * 1000, 1),
            "p90": round(_percentile(latencies, 0.90) * 1000, 1),
            "p95": round(_percentile(latencies, 0.95) * 1000, 1),
            "p99": round(_percentile(latencies, 0.99) * 1000, 1),
        },
    }
    if tenant:
        result["tenant"] = tenant
    if len(classes) > 1 or "" not in per_class:
        # per-class breakdown only when the run was actually classed
        result["classes"] = classes
    return result


async def run_rollout(url: str, *, proxy: str = "", workers: int = 4,
                      manifest_path: str = "",
                      connect_timeout_s: float = 10.0) -> dict:
    """One sharded-checkpoint rollout wave: ``workers`` clients each pull
    a DISJOINT subset of the manifest's shards (round-robin split) as
    ranged GETs through the proxy, recording per-shard ready timestamps —
    the client-side shape of the serving-fleet rollout the PR-14 bench
    models pod-wide. The report carries per-shard fetch p50/p99 and the
    wave's time-to-all-shards makespan."""
    import aiohttp

    # dflint: disable=DF001 — one KB-scale manifest read on stress's CLI-private loop
    with open(manifest_path, encoding="utf-8") as f:
        raw = json.load(f)
    entries = raw.get("shards", raw) if isinstance(raw, dict) else raw
    if not entries:
        raise SystemExit("stress: empty shard manifest")
    subsets = {i: entries[i::workers] for i in range(workers)}
    shard_lat: dict[str, float] = {}
    ready_at: dict[str, float] = {}
    errors = 0
    total_bytes = 0

    t_start = time.monotonic()

    async def worker(session: aiohttp.ClientSession, i: int) -> None:
        nonlocal errors, total_bytes
        for e in subsets[i]:
            start = int(e["range_start"])
            end = start + int(e["range_size"]) - 1
            t0 = time.monotonic()
            try:
                async with session.get(
                        url, proxy=proxy or None,
                        headers={"Range": f"bytes={start}-{end}"}) as resp:
                    got = 0
                    async for chunk in resp.content.iter_chunked(1 << 20):
                        got += len(chunk)
                    if resp.status != 206 or got != int(e["range_size"]):
                        # a 200 full-body answer means the server ignored
                        # the Range: every "shard" would be the whole
                        # checkpoint and the per-shard numbers fiction —
                        # count it as an error, don't launder it
                        errors += 1
                        continue
                    total_bytes += got
                    shard_lat[e["name"]] = time.monotonic() - t0
                    ready_at[e["name"]] = time.monotonic() - t_start
            except Exception:  # noqa: BLE001 - counted, wave goes on
                errors += 1

    timeout = aiohttp.ClientTimeout(total=None,
                                    sock_connect=connect_timeout_s)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        await asyncio.gather(*(worker(session, i) for i in range(workers)))
    elapsed = time.monotonic() - t_start
    lats = sorted(shard_lat.values())
    return {
        "url": url,
        "rollout_workers": workers,
        "shards": len(entries),
        "shards_ready": len(ready_at),
        "errors": errors,
        "bytes": total_bytes,
        "makespan_s": round(max(ready_at.values(), default=0.0), 3),
        "duration_s": round(elapsed, 2),
        "shard_fetch_ms": {
            "p50": round(_percentile(lats, 0.50) * 1000, 1),
            "p99": round(_percentile(lats, 0.99) * 1000, 1),
        },
        "per_worker_shards": {i: [e["name"] for e in subsets[i]]
                              for i in range(workers)},
    }


async def _run_with_chaos(args) -> dict:
    """Arm the chaos script (remote daemon or in-process), run the load,
    ALWAYS disarm — a stress run must not leave a live daemon wedged."""
    import aiohttp

    from ..common import faultgate

    if getattr(args, "byzantine", None):
        # --byzantine PCT: the target daemon becomes a poisoner — flip
        # bytes in PCT% of the ranges it serves (site upload.serve,
        # deterministic striding) so the pod's verdict/quarantine plane
        # can be exercised against a live swarm
        clause = f"upload.serve=corrupt:pct={int(args.byzantine)}:n=-1"
        args.chaos = f"{args.chaos};{clause}" if args.chaos else clause
        if not args.chaos_target:
            raise SystemExit("stress: --byzantine needs --chaos-target "
                             "http://daemon:upload_port (the daemon that "
                             "will serve corrupt bytes)")
    target = args.chaos_target.rstrip("/")
    session = None
    try:
        if args.chaos and target:
            session = aiohttp.ClientSession()
            async with session.post(f"{target}/debug/faults",
                                    data=args.chaos) as resp:
                if resp.status != 200:
                    raise SystemExit(
                        f"chaos arm failed: HTTP {resp.status} "
                        f"{await resp.text()} (is upload.debug_endpoints "
                        f"on?)")
        elif args.chaos:
            # in-process arming only matters when fabric code runs in THIS
            # process (run_stress issues plain HTTP GETs, which cross no
            # faultgate site) — without a target the script is almost
            # certainly meant for a daemon, so say so loudly
            print("warning: --chaos without --chaos-target arms faults in "
                  "this process only; a separate daemon is NOT affected "
                  "(pass --chaos-target http://daemon:upload_port)",
                  file=sys.stderr)
            faultgate.arm_script(args.chaos)
        return await run_stress(
            args.url, proxy=args.proxy, concurrency=args.concurrency,
            duration_s=args.duration, tenant=args.tenant,
            class_mix=parse_class_mix(args.priority, args.concurrency))
    finally:
        if session is not None:
            try:
                async with session.delete(f"{target}/debug/faults"):
                    pass
            finally:
                await session.close()
        elif args.chaos:
            faultgate.reset()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="dfstress", description="concurrent download load generator")
    p.add_argument("--url", required=True)
    p.add_argument("--proxy", default="",
                   help="http proxy (the daemon's mirror), e.g. "
                        "http://127.0.0.1:65001")
    p.add_argument("-c", "--concurrency", type=int, default=8)
    p.add_argument("-d", "--duration", type=float, default=10.0)
    p.add_argument("--tenant", default="",
                   help="tenant the load is accounted to "
                   "(X-Dragonfly-Tenant on every request)")
    p.add_argument("--priority", action="append", default=[],
                   metavar="CLASS[:WORKERS]",
                   help="mixed-class load: allocate workers to a QoS "
                   "class (critical/standard/bulk), repeatable — e.g. "
                   "'--priority critical:2 --priority bulk:6'. The "
                   "report then breaks out per-class p50/p99 latency "
                   "and 429-shed counts. Unallocated workers run as "
                   "standard; with no --priority the run is classless.")
    p.add_argument("--rollout", type=int, default=0, metavar="WORKERS",
                   help="sharded-rollout scenario: WORKERS clients each "
                   "pull a disjoint subset of --shard-manifest's shards "
                   "as ranged GETs (one wave, not duration-based); the "
                   "report carries per-shard fetch p50/p99 and the "
                   "wave's time-to-all-shards makespan")
    p.add_argument("--shard-manifest", default="", dest="shard_manifest",
                   help="shard-manifest JSON path for --rollout "
                   "(same schema as dfget --shard-manifest)")
    p.add_argument("--chaos", default="",
                   help="faultgate script to arm for the run, e.g. "
                        "'piece.wire=delay:0.2:n=-1' (docs/RESILIENCE.md)")
    p.add_argument("--byzantine", nargs="?", const=30, type=int,
                   default=None, metavar="PCT",
                   help="arm the --chaos-target daemon as a byzantine "
                        "poisoner: corrupt PCT%% (default 30) of the "
                        "ranges it serves (site upload.serve), disarmed "
                        "after the run. The report gains per-parent "
                        "corrupt-verdict counts swept from the "
                        "--pod-report daemons' /debug/verdicts — the "
                        "live proof the quarantine plane engaged")
    p.add_argument("--chaos-target", default="",
                   help="daemon debug base URL (http://host:upload_port); "
                        "the script is POSTed to /debug/faults there and "
                        "disarmed after the run")
    p.add_argument("--pex-dump", default="",
                   help="daemon upload base URL (http://host:upload_port); "
                        "after the run, attach its /debug/pex snapshot "
                        "(gossip membership + swarm index) to the report — "
                        "pairs with --chaos 'pex.gossip=...' runs")
    p.add_argument("--ctrl-report", default="",
                   help="scheduler debug host:port (the --debug-port); "
                        "after the run, attach its /debug/ctrl snapshot "
                        "(rulings/sec, worst ruling phase, bytes of "
                        "scheduler state) so the report says what the "
                        "control plane spent, not just what this "
                        "client saw")
    p.add_argument("--fleet-report", default="",
                   help="scheduler debug host:port (the --debug-port); "
                        "after the run, attach its compact /debug/fleet "
                        "snapshot (pulse rollups, anomaly counts, active "
                        "episodes, incident ids) so a stress/chaos report "
                        "says what the FLEET's telemetry plane saw, not "
                        "just what this client measured")
    p.add_argument("--pod-report", default="",
                   help="comma-separated daemon upload host:port set; "
                        "after the run, attach the podscope pod summary "
                        "(distribution-tree depth, makespan, origin "
                        "amplification, bottleneck edge, breaches) so a "
                        "stress/chaos report says what the POD did, not "
                        "just what this client saw")
    args = p.parse_args(argv)
    if args.rollout:
        if not args.shard_manifest:
            raise SystemExit("stress: --rollout needs --shard-manifest")
        result = asyncio.run(run_rollout(
            args.url, proxy=args.proxy, workers=args.rollout,
            manifest_path=args.shard_manifest))
        if args.pod_report:
            result["podscope"] = _pod_report(args.pod_report)
        if args.ctrl_report:
            result["ctrl"] = _ctrl_report(args.ctrl_report)
        if args.fleet_report:
            result["fleet"] = _fleet_report(args.fleet_report)
        print(json.dumps(result))
        return 1 if result["shards_ready"] == 0 else 0
    result = asyncio.run(_run_with_chaos(args))
    if args.chaos:
        result["chaos"] = args.chaos
    if args.pex_dump:
        result["pex"] = asyncio.run(_fetch_pex(args.pex_dump.rstrip("/")))
    if args.pod_report:
        result["podscope"] = _pod_report(args.pod_report)
    if args.ctrl_report:
        result["ctrl"] = _ctrl_report(args.ctrl_report)
    if args.fleet_report:
        result["fleet"] = _fleet_report(args.fleet_report)
    if args.byzantine:
        result["byzantine"] = {
            "pct": int(args.byzantine),
            "target": args.chaos_target,
            # per-parent corrupt counts as the DOWNLOADERS saw them:
            # who recorded verdicts against whom, and who got shunned
            "verdicts": _verdict_report(args.pod_report),
        }
    print(json.dumps(result))
    return 1 if result["requests"] == result["errors"] else 0


def _verdict_report(pod: str) -> dict:
    """Per-parent corrupt-verdict counts swept from each daemon's
    /debug/verdicts (the --byzantine report body). Diagnostics must not
    fail a run; no pod set = nothing to sweep. Deliberately a direct
    sweep rather than a ride-along on --pod-report's podscope collection:
    the podscope compaction drops the per-parent COUNT columns this
    report exists to show, and one extra GET per daemon on a diagnostics
    path is cheaper than a second compaction contract."""
    if not pod:
        return {"note": "pass --pod-report to sweep /debug/verdicts"}
    import urllib.error
    import urllib.request

    out: dict = {}
    for addr in (a.strip() for a in pod.split(",") if a.strip()):
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/debug/verdicts", timeout=5.0) as resp:
                snap = json.loads(resp.read())
        except (OSError, ValueError) as exc:
            out[addr] = {"error": str(exc) or type(exc).__name__}
            continue
        parents = snap.get("parents") or {}
        out[addr] = {
            "self_quarantined": snap.get("self_quarantined", False),
            "corrupt": {p: row.get("codes", {}).get("corrupt", 0)
                        for p, row in parents.items()
                        if row.get("codes", {}).get("corrupt")},
            "shunned": [p for p, row in parents.items()
                        if row.get("shunned")],
        }
    return out


def _fleet_report(scheduler: str) -> dict:
    """Compact fleet-pulse snapshot for the stress report (dfdiag
    --fleet's /debug/fleet?compact=1, further compacted): pulse rollups,
    anomaly counts, any active episodes, and incident ids — a chaos run
    that tripped the detector should say so in its own report.
    Diagnostics must not fail a run."""
    try:
        from .dfdiag import _get
        snap = _get(f"http://{scheduler}/debug/fleet?compact=1",
                    timeout_s=5.0)
        return {
            "daemons": snap.get("daemons", 0),
            "ingested": snap.get("ingested", 0),
            "ignored": snap.get("ignored", 0),
            "fleet": snap.get("fleet"),
            "anomaly_counts": snap.get("anomaly_counts"),
            "active": snap.get("active"),
            "incidents": snap.get("incidents", 0),
            "incident_ids": snap.get("incident_ids"),
        }
    except Exception as exc:  # noqa: BLE001 - diagnostics must not fail a run
        return {"error": str(exc)}


def _ctrl_report(scheduler: str) -> dict:
    """Control-plane snapshot for the stress report (dfdiag --ctrl's
    /debug/ctrl, compacted): rulings/sec, the worst phase by total self
    time, and state bytes — so a stress/chaos report says what the
    SCHEDULER spent on its rulings, not just what this client saw.
    Diagnostics must not fail a run."""
    try:
        from .dfdiag import fetch_ctrl
        snap = fetch_ctrl(scheduler, timeout_s=5.0)
        phases = snap.get("phases") or {}
        worst = (max(phases, key=lambda n: phases[n]["self_ms"])
                 if phases else "")
        rul = snap.get("rulings") or {}
        return {
            "armed": snap.get("armed"),
            "rulings": rul.get("total", 0),
            "rulings_per_sec_busy": rul.get("per_sec_busy", 0.0),
            "rulings_per_sec_60s": rul.get("per_sec_60s", 0.0),
            "worst_phase": worst,
            "worst_phase_ms": (phases[worst]["self_ms"] if worst else 0.0),
            "queue_wait_ms": snap.get("queue_wait_ms"),
            "state_bytes": snap.get("state_bytes"),
        }
    except Exception as exc:  # noqa: BLE001 - diagnostics must not fail a run
        return {"error": str(exc)}


def _pod_report(pod: str) -> dict:
    """Podscope summary for the stress report: compact per-task numbers +
    the breach list and verdict (diagnostics must not fail a run)."""
    from ..common import podscope
    try:
        addrs = [a.strip() for a in pod.split(",") if a.strip()]
        report = podscope.aggregate(podscope.collect_pod(addrs))
        return {
            "tasks": {tid: podscope.bench_summary(t)
                      for tid, t in report["tasks"].items()},
            "unreachable": report["unreachable"],
            "breaches": report["breaches"],
            "verdict": report["verdict"],
        }
    except Exception as exc:  # noqa: BLE001 - diagnostics must not fail a run
        return {"error": str(exc)}


async def _fetch_pex(base: str) -> dict:
    import aiohttp
    try:
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{base}/debug/pex",
                                   timeout=aiohttp.ClientTimeout(
                                       total=5.0)) as resp:
                return await resp.json()
    except Exception as exc:  # noqa: BLE001 - diagnostics must not fail a run
        return {"error": str(exc)}


if __name__ == "__main__":
    sys.exit(main())
