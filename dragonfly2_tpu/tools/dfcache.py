"""dfcache: stat/import/export/delete files in the P2P cache.

Role parity: reference ``cmd/dfcache`` + ``client/dfcache/dfcache.go``
(Stat :46, Import :112, Export :174, Delete :244) — cache entries are tasks
keyed by a ``cache://<id>`` URL (the reference's content-id equivalent).

Usage:
    python -m dragonfly2_tpu.tools.dfcache stat ID
    python -m dragonfly2_tpu.tools.dfcache import ID -I /path/in
    python -m dragonfly2_tpu.tools.dfcache export ID -O /path/out
    python -m dragonfly2_tpu.tools.dfcache delete ID
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ..common.dfpath import DFPath
from ..common.errors import DFError
from ..idl.messages import (DeleteTaskRequest, ExportTaskRequest,
                            ImportTaskRequest, StatTaskDaemonRequest, UrlMeta)
from ..rpc.client import Channel, ServiceClient


def cache_url(cache_id: str) -> str:
    return f"cache://local/{cache_id}"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dfcache",
                                description="P2P cache operations")
    p.add_argument("op", choices=["stat", "import", "export", "delete"])
    p.add_argument("id", help="cache entry id")
    p.add_argument("-I", "--input", default="", help="file to import")
    p.add_argument("-O", "--output", default="", help="export destination")
    p.add_argument("--tag", default="")
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--daemon-sock", default="")
    p.add_argument("--local-only", action="store_true",
                   help="stat/export only from this daemon's storage")
    return p


async def run(args: argparse.Namespace) -> int:
    sock = args.daemon_sock or DFPath().daemon_sock()
    ch = Channel(f"unix:{sock}")
    client = ServiceClient(ch, "df.daemon.Daemon")
    meta = UrlMeta(tag=args.tag)
    url = cache_url(args.id)
    try:
        if args.op == "stat":
            stat = await client.unary("StatTask", StatTaskDaemonRequest(
                url=url, url_meta=meta, local_only=args.local_only),
                timeout=args.timeout)
            print(json.dumps({"id": stat.id, "state": stat.state,
                              "content_length": stat.content_length,
                              "pieces": stat.total_piece_count}))
        elif args.op == "import":
            if not args.input:
                print("import requires -I", file=sys.stderr)
                return 2
            stat = await client.unary("ImportTask", ImportTaskRequest(
                path=args.input, url=url, url_meta=meta),
                timeout=args.timeout)
            print(json.dumps({"id": stat.id,
                              "content_length": stat.content_length}))
        elif args.op == "export":
            if not args.output:
                print("export requires -O", file=sys.stderr)
                return 2
            await client.unary("ExportTask", ExportTaskRequest(
                url=url, output=args.output, url_meta=meta,
                timeout_s=args.timeout, local_only=args.local_only),
                timeout=args.timeout + 5)
            print(json.dumps({"exported": args.output}))
        elif args.op == "delete":
            await client.unary("DeleteTask", DeleteTaskRequest(
                url=url, url_meta=meta), timeout=args.timeout)
            print(json.dumps({"deleted": args.id}))
        return 0
    except DFError as exc:
        print(f"dfcache: {exc.code.name}: {exc.message}", file=sys.stderr)
        return 1
    finally:
        await ch.close()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    raise SystemExit(main())
