"""dfsched: explain scheduler rulings — decomposition, exclusions, payoff.

Reads the decision ledger (scheduler/decision_ledger.py) and answers
"why did child X get parent Y, what did the runner-up score, and how did
the choice pay off": every ``kind=decision`` row is rendered with its
per-term score breakdown next to each candidate's total, every
filtered-out parent with its exclusion reason, sticky-refresh kept/fresh
marks — and, when outcome rows are present, the pieces/bytes each chosen
parent actually served plus the observed edge bandwidth beside the
predicted rank.

Sources:
  --records PATH   a records JSONL file (or the directory holding
                   download.jsonl; the rotated .1 half is read first) —
                   decisions AND their kind=piece / kind=edge outcome
                   rows, stitched offline;
  --scheduler H:P  the live /debug/decisions ring on the scheduler's
                   --debug-port (no outcome join: the ring holds rulings,
                   the records file holds what happened next).

``--replay learned`` re-scores every logged ruling under the learned
parent-quality model next to the heuristic (the same pure replay math as
``dfbench --pr8``/``--pr19``: ``scheduler/decision_ledger.py``) and
renders the choice FLIPS — rulings where the learned model promotes a
different parent — with the per-term score decomposition of both picks
side by side, so "what did the model see that the heuristic didn't"
reads straight off the terminal. The model comes from ``--model
blob.npz`` (a ``trainer/params_io.py`` artifact) or, when omitted, a
seeded fit over the records themselves (``trainer/pipeline.py``).

Usage:
    python -m dragonfly2_tpu.tools.dfsched --records records/ <task_id>
    python -m dragonfly2_tpu.tools.dfsched --records download.jsonl --stats
    python -m dragonfly2_tpu.tools.dfsched --scheduler 127.0.0.1:65100
    python -m dragonfly2_tpu.tools.dfsched --records records/ --child f3a9
    python -m dragonfly2_tpu.tools.dfsched --records records/ \
        --replay learned [--model bandwidth_mlp.npz]

Exit codes (CI contract, same shape as dfdiag): 0 ok, 1 fetch/IO
failure, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..common.podscope import _fmt_bytes, _get_json
from ..scheduler.decision_ledger import stitch_outcomes
from ..scheduler.evaluator import SCORE_TERMS

EXIT_OK = 0
EXIT_IO = 1
EXIT_USAGE = 2

# rendered term columns, in weight-table order
_TERM_COLS = tuple(name for name, _ in SCORE_TERMS)
_TERM_HDR = {"piece": "piece", "upload_success": "upsucc",
             "free_upload": "free", "host_type": "host",
             "locality": "local"}


def load_rows(path: str) -> list[dict]:
    """Rows from a records JSONL file or a records dir (rotated .1 half
    first so decisions precede their outcomes in replay order)."""
    if os.path.isdir(path):
        base = os.path.join(path, "download.jsonl")
        paths = [p for p in (base + ".1", base) if os.path.exists(p)]
        if not paths:
            raise FileNotFoundError(f"no download.jsonl under {path}")
    else:
        paths = [path]
    rows: list[dict] = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue       # torn tail line of a live file
    return rows


def render_decision(d: dict, *, max_candidates: int = 10) -> str:
    """One ruling, human-readable. Pure function over a stitched (or raw)
    decision row so it is testable offline and reusable by dfdiag
    --decisions."""
    chosen = d.get("chosen") or []
    kept = set(d.get("kept") or [])
    fresh = set(d.get("fresh") or [])
    outcomes = d.get("outcomes") or {}
    edges = d.get("edges") or {}
    if d.get("decision_kind") == "quarantine":
        # a quarantine-ladder ruling: no candidate table — the host, the
        # transition, and the evidence ARE the ruling
        return (f"decision {d.get('decision_id', '?')} (quarantine)  "
                f"host {d.get('host_id', '?')[-28:]}: "
                f"{d.get('from_state', '?')} -> {d.get('to_state', '?')}"
                f"  [{d.get('why', '')}]"
                f"  evidence={d.get('corrupt_evidence', 0)}"
                f" reporters={len(d.get('reporters') or [])}"
                + ("  SELF-FLAGGED" if d.get("self_flagged") else ""))
    out = [f"decision {d.get('decision_id', '?')} "
           f"({d.get('decision_kind', '?')}, {d.get('evaluator', '?')})  "
           f"task {d.get('task_id', '?')[:16]}  "
           f"child {d.get('peer_id', '?')[-16:]}"]
    cands = d.get("candidates") or []
    if cands:
        hdr = (f"  {'':>2} {'rank':>4} {'peer':>18} {'total':>7} "
               + " ".join(f"{_TERM_HDR[c]:>6}" for c in _TERM_COLS))
        out.append(hdr)
        for c in cands[:max_candidates]:
            pid = c.get("peer_id", "")
            mark = "*" if pid in chosen else " "
            terms = c.get("terms") or {}
            line = (f"  {mark:>2} {c.get('rank', 0):>4} {pid[-18:]:>18} "
                    f"{c.get('total', 0.0):>7.4f} "
                    + " ".join(f"{terms.get(t, 0.0):>6.3f}"
                               for t in _TERM_COLS))
            notes = []
            if pid == (chosen[0] if chosen else None):
                notes.append("chosen (main)")
            elif pid in chosen:
                notes.append("chosen")
            if pid in kept:
                notes.append("kept")
            elif pid in fresh and pid in chosen:
                notes.append("fresh")
            sub = c.get("substituted")
            if sub:
                notes.append("/".join(f"{k}<-{v}" for k, v in sub.items()))
            if notes:
                line += "   " + ", ".join(notes)
            out.append(line)
        if len(cands) > max_candidates:
            out.append(f"     … +{len(cands) - max_candidates} more "
                       f"candidates")
    elif d.get("decision_kind") == "preempt":
        pre = d.get("preempted") or {}
        out.append(
            f"  preempted: {pre.get('victim_class', '?')} child "
            f"{pre.get('victim_peer_id', '?')[-16:]}"
            + (f" (tenant {pre['victim_tenant']})"
               if pre.get("victim_tenant") else "")
            + f" lost parent {pre.get('parent_id', '?')[-16:]} so this "
            f"{d.get('qos_class', 'critical')} child could schedule")
    else:
        out.append("  (no legal candidates — every parent filtered)")
    excl = d.get("excluded") or []
    if excl:
        out.append("  excluded: " + "; ".join(
            f"{e.get('peer_id', '')[-14:]} {e.get('reason', '?')}"
            for e in excl))
    if outcomes:
        rank_of = {c.get("peer_id"): c.get("rank")
                   for c in d.get("candidates") or []}
        for pid, o in sorted(outcomes.items(),
                             key=lambda kv: -kv[1]["pieces"]):
            mean = o["cost_ms"] / o["pieces"] if o["pieces"] else 0.0
            line = (f"  outcome: {pid[-16:]} served {o['pieces']} "
                    f"piece(s) / {_fmt_bytes(o['bytes'])}, "
                    f"mean {mean:.1f}ms/piece (predicted rank "
                    f"{rank_of.get(pid, '?')})")
            edge = edges.get(pid)
            if edge and edge.get("bandwidth_bps"):
                line += (f", observed edge "
                         f"{_fmt_bytes(edge['bandwidth_bps'])}/s")
            out.append(line)
        runner = next((c for c in d.get("candidates") or []
                       if c.get("peer_id") not in chosen), None)
        if runner is not None:
            served = outcomes.get(runner.get("peer_id"), {}).get("pieces", 0)
            out.append(f"  runner-up: {runner.get('peer_id', '')[-16:]} "
                       f"scored {runner.get('total', 0.0):.4f}, "
                       f"served {served} piece(s)")
    return "\n".join(out)


def replay_learned(rows: list[dict], infer) -> dict:
    """Heuristic-vs-learned counterfactual over raw record rows, reusing
    the ledger's replay machinery wholesale. Returns the summary plus one
    entry per choice FLIP carrying both picks' per-term decompositions
    and their scores under each evaluator — the data ``render_flip``
    draws and ``--json`` emits verbatim."""
    from ..scheduler.decision_ledger import (replay_decisions, replay_regret,
                                             rescore_candidate,
                                             rescore_decision)
    decisions = [r for r in rows
                 if r.get("kind") == "decision" and r.get("candidates")]
    summary = replay_decisions(rows, evaluators=("default", "ml"),
                               infer=infer)
    regret = replay_regret(rows, evaluators=("default", "ml"), infer=infer)
    flips = []
    for d in decisions:
        ranked_h = rescore_decision(d, "default")
        ranked_m = rescore_decision(d, "ml", infer)
        if not ranked_h or not ranked_m or ranked_h[0] == ranked_m[0]:
            continue
        cands = {c.get("peer_id", ""): c for c in d["candidates"]}
        picks = {}
        for who, pid in (("heuristic", ranked_h[0]), ("learned",
                                                      ranked_m[0])):
            c = cands[pid]
            terms = c.get("terms") or {}
            picks[who] = {
                "peer_id": pid,
                "terms": {t: round(float(terms.get(t, 0.0)), 4)
                          for t in _TERM_COLS},
                "score_heuristic": round(rescore_candidate(
                    c, "default", d.get("host_id", "")), 4),
                "score_learned": round(rescore_candidate(
                    c, "ml", d.get("host_id", ""), infer), 4),
            }
        flips.append({"decision_id": d.get("decision_id", ""),
                      "task_id": d.get("task_id", ""),
                      "peer_id": d.get("peer_id", ""), **picks})
    return {"decisions_scored": len(decisions), "summary": summary,
            "regret": regret, "flips": flips}


def render_flip(flip: dict) -> str:
    """One choice flip: both picks' logged per-term decomposition side by
    side with the deltas, then each pick's score under each evaluator."""
    h, m = flip["heuristic"], flip["learned"]
    out = [f"flip {flip['decision_id']}  task {flip['task_id'][:16]}  "
           f"child {flip['peer_id'][-16:]}: heuristic keeps "
           f"{h['peer_id'][-16:]}, learned promotes {m['peer_id'][-16:]}",
           f"  {'':>10} {'peer':>18} "
           + " ".join(f"{_TERM_HDR[t]:>6}" for t in _TERM_COLS)
           + f" {'score_h':>8} {'score_ml':>8}"]
    for who, pick in (("heuristic", h), ("learned", m)):
        out.append(
            f"  {who:>10} {pick['peer_id'][-18:]:>18} "
            + " ".join(f"{pick['terms'][t]:>6.3f}" for t in _TERM_COLS)
            + f" {pick['score_heuristic']:>8.4f}"
            f" {pick['score_learned']:>8.4f}")
    out.append(
        f"  {'delta':>10} {'':>18} "
        + " ".join(f"{m['terms'][t] - h['terms'][t]:>+6.3f}"
                   for t in _TERM_COLS)
        + f" {m['score_heuristic'] - h['score_heuristic']:>+8.4f}"
        f" {m['score_learned'] - h['score_learned']:>+8.4f}")
    return "\n".join(out)


def render_replay(rep: dict, model_desc: str, limit: int = 8) -> str:
    pair = rep["summary"]["pairs"]["default_vs_ml"]
    logged = rep["summary"]["logged_choice_agreement"]
    out = [f"replay: heuristic vs learned ({model_desc}) over "
           f"{rep['decisions_scored']} ruling(s)",
           f"  choice flips: {len(rep['flips'])} "
           f"({pair['choice_flip_rate']:.1%})   rank agreement: "
           f"{pair['rank_agreement']:.3f}   logged-choice agreement: "
           f"heuristic {logged['default']:.3f} / learned "
           f"{logged['ml']:.3f}"]
    reg = rep["regret"]
    if reg["decisions_judged"]:
        ev = reg["evaluators"]
        out.append(
            f"  observed-bandwidth regret over {reg['decisions_judged']} "
            f"judged ruling(s): heuristic "
            f"{ev['default']['mean_regret']:.4f} vs learned "
            f"{ev['ml']['mean_regret']:.4f}   best-pick rate: "
            f"{ev['default']['best_pick_rate']:.1%} vs "
            f"{ev['ml']['best_pick_rate']:.1%}")
    else:
        out.append("  (no outcome rows joined — regret needs "
                   "kind=piece rows beside the decisions)")
    for flip in rep["flips"][-limit:]:
        out.append("")
        out.append(render_flip(flip))
    if len(rep["flips"]) > limit:
        out.append(f"\n  … +{len(rep['flips']) - limit} more flip(s)")
    return "\n".join(out)


def render_stats(stitched: dict) -> str:
    cov = stitched["coverage"]
    decisions = stitched["decisions"]
    by_kind: dict[str, int] = {}
    excl: dict[str, int] = {}
    for d in decisions:
        by_kind[d.get("decision_kind", "?")] = \
            by_kind.get(d.get("decision_kind", "?"), 0) + 1
        for e in d.get("excluded") or []:
            excl[e.get("reason", "?")] = excl.get(e.get("reason", "?"), 0) + 1
    out = [f"decisions: {len(decisions)} "
           f"({', '.join(f'{k}={v}' for k, v in sorted(by_kind.items()))})",
           f"outcome join: {cov['joined']}/{cov['piece_rows']} piece rows "
           f"stitched to a logged decision ({cov['ratio']:.1%})"]
    if excl:
        out.append("exclusions: " + ", ".join(
            f"{r}={n}" for r, n in sorted(excl.items(), key=lambda kv: -kv[1])))
    return "\n".join(out)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dfsched",
        description="decision-ledger inspector: score decomposition, "
                    "exclusions, outcome joins")
    p.add_argument("task_id", nargs="?", default="",
                   help="task id (prefix ok); default: the task with the "
                   "most logged decisions")
    p.add_argument("--records", default="",
                   help="records JSONL file, or the scheduler records dir "
                   "holding download.jsonl")
    p.add_argument("--scheduler", default="",
                   help="scheduler --debug-port host:port serving "
                   "/debug/decisions (live ring; no outcome join)")
    p.add_argument("--child", default="",
                   help="filter to one child peer id (suffix ok)")
    p.add_argument("--limit", type=int, default=8,
                   help="newest-N decisions to render (default 8)")
    p.add_argument("--stats", action="store_true",
                   help="coverage + exclusion summary instead of rulings")
    p.add_argument("--replay", default="", choices=("", "learned"),
                   help="'learned': re-score every ruling under the "
                   "learned parent-quality model vs the heuristic and "
                   "render the choice flips with per-term deltas "
                   "(needs --records)")
    p.add_argument("--model", default="",
                   help="serialized model blob for --replay learned "
                   "(trainer/params_io.py artifact); omit to fit one "
                   "from the records themselves")
    p.add_argument("--seed", type=int, default=0,
                   help="fit seed when --replay learned fits from the "
                   "records (ignored with --model)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable JSON instead of rendered text")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="HTTP timeout for --scheduler fetches")
    return p


def _pick_task(decisions: list[dict], prefix: str) -> str:
    if prefix:
        return prefix
    counts: dict[str, int] = {}
    for d in decisions:
        tid = d.get("task_id", "")
        counts[tid] = counts.get(tid, 0) + 1
    return max(counts, key=counts.get) if counts else ""


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.replay:
            if not args.records:
                # the live ring would work too, but its rows lack the
                # joined outcomes the regret judgment needs — keep the
                # mode honest and file-fed
                print("dfsched: --replay needs --records PATH",
                      file=sys.stderr)
                return EXIT_USAGE
            rows = load_rows(args.records)
            from ..trainer.serving import make_mlp_infer
            if args.model:
                with open(args.model, "rb") as f:
                    infer = make_mlp_infer(f.read())
                desc = (f"model {getattr(infer, 'version', '?')} from "
                        f"{os.path.basename(args.model)}")
            else:
                from ..trainer.pipeline import train_decision_model
                fitted = train_decision_model(rows, seed=args.seed,
                                              use_mesh=False)
                if fitted is None:
                    print("dfsched: too few usable rows to fit a replay "
                          "model — pass --model blob.npz or more records",
                          file=sys.stderr)
                    return EXIT_IO
                infer = make_mlp_infer(fitted[0])
                desc = (f"model {fitted[1]['version']} fit from these "
                        f"records, seed {args.seed}")
            rep = replay_learned(rows, infer)
            if args.json:
                print(json.dumps({"model": desc, **rep}, indent=2))
            else:
                print(render_replay(rep, desc, limit=args.limit))
            return EXIT_OK
        if args.scheduler:
            # fetch the whole ring (bounded server-side at DEFAULT_RING_ROWS)
            # and slice locally: asking for only --limit rows would truncate
            # to the newest N across ALL tasks BEFORE the task/child filter
            # runs, under-filling the output exactly on a busy scheduler
            from ..scheduler.decision_ledger import DEFAULT_RING_ROWS
            snap = _get_json(
                f"http://{args.scheduler}/debug/decisions"
                f"?task={args.task_id}&peer={args.child}"
                f"&limit={max(args.limit, DEFAULT_RING_ROWS)}", args.timeout)
            stitched = {"decisions": snap.get("decisions") or [],
                        "coverage": {"piece_rows": 0, "joined": 0,
                                     "ratio": 1.0}}
            stats = snap.get("stats") or {}
        elif args.records:
            rows = load_rows(args.records)
            stitched = stitch_outcomes(rows)
            stats = {}
        else:
            print("dfsched: need --records PATH or --scheduler host:port",
                  file=sys.stderr)
            return EXIT_USAGE
        decisions = stitched["decisions"]
        task = _pick_task(decisions, args.task_id)
        picked = [d for d in decisions
                  if d.get("task_id", "").startswith(task)
                  and (not args.child
                       or d.get("peer_id", "").endswith(args.child))]
        if args.json:
            print(json.dumps({"coverage": stitched["coverage"],
                              "stats": stats,
                              "decisions": picked[-args.limit:]}, indent=2))
            return EXIT_OK
        if args.stats:
            if stats:
                print(f"ledger: {json.dumps(stats)}")
            print(render_stats(stitched))
            return EXIT_OK
        if not picked:
            print("dfsched: no decisions recorded"
                  + (f" for task {task[:16]}" if task else ""),
                  file=sys.stderr)
            return EXIT_OK
        for d in picked[-args.limit:]:
            print(render_decision(d))
            print()
        print(render_stats(stitched))
        return EXIT_OK
    except (OSError, ValueError) as exc:
        # unreachable scheduler / missing or torn file: one line, no
        # traceback — same CI contract as dfdiag
        print(f"dfsched: {type(exc).__name__}: {exc}", file=sys.stderr)
        return EXIT_IO


if __name__ == "__main__":
    sys.exit(main())
