"""dfstore: object-storage gateway client + CLI.

Role parity: reference ``cmd/dfstore`` + ``client/dfstore/dfstore.go``
(GetObject/PutObject/CopyObject/DeleteObject/IsObjectExist against the
daemon's object gateway).

Usage:
    python -m dragonfly2_tpu.tools.dfstore get  BUCKET KEY -O /path/out
    python -m dragonfly2_tpu.tools.dfstore put  BUCKET KEY -I /path/in
    python -m dragonfly2_tpu.tools.dfstore stat BUCKET KEY
    python -m dragonfly2_tpu.tools.dfstore rm   BUCKET KEY
    python -m dragonfly2_tpu.tools.dfstore ls   BUCKET
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from urllib.parse import quote

import aiohttp


class Dfstore:
    """HTTP client for the daemon's object gateway."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")

    def _url(self, bucket: str, key: str = "") -> str:
        base = f"{self.endpoint}/buckets/{quote(bucket)}/objects"
        return f"{base}/{quote(key)}" if key else base

    async def get_object(self, bucket: str, key: str, output: str) -> int:
        async with aiohttp.ClientSession() as http:
            async with http.get(self._url(bucket, key)) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"GET {key}: HTTP {resp.status}")
                n = 0
                # dflint: disable=DF001 — dfstore runs a CLI-private loop; blocking it slows only this invocation
                with open(output, "wb") as f:
                    async for chunk in resp.content.iter_chunked(1 << 20):
                        # dflint: disable=DF001 — CLI-private loop, see above
                        f.write(chunk)
                        n += len(chunk)
                return n

    async def put_object(self, bucket: str, key: str, path: str) -> None:
        async with aiohttp.ClientSession() as http:
            # dflint: disable=DF001 — CLI-private loop; aiohttp streams the handle itself
            with open(path, "rb") as f:
                async with http.put(self._url(bucket, key), data=f) as resp:
                    if resp.status not in (200, 201):
                        raise RuntimeError(f"PUT {key}: HTTP {resp.status}")

    async def is_object_exist(self, bucket: str, key: str) -> int | None:
        async with aiohttp.ClientSession() as http:
            async with http.head(self._url(bucket, key)) as resp:
                if resp.status != 200:
                    return None
                return int(resp.headers.get("Content-Length", -1))

    async def delete_object(self, bucket: str, key: str) -> None:
        async with aiohttp.ClientSession() as http:
            async with http.delete(self._url(bucket, key)) as resp:
                if resp.status not in (200, 204):
                    raise RuntimeError(f"DELETE {key}: HTTP {resp.status}")

    async def list_objects(self, bucket: str) -> list[dict]:
        async with aiohttp.ClientSession() as http:
            async with http.get(self._url(bucket)) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"LIST {bucket}: HTTP {resp.status}")
                return await resp.json()

    async def copy_object(self, bucket: str, src: str, dst: str) -> None:
        import tempfile
        with tempfile.NamedTemporaryFile() as tmp:
            await self.get_object(bucket, src, tmp.name)
            await self.put_object(bucket, dst, tmp.name)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dfstore",
                                description="object gateway operations")
    p.add_argument("op", choices=["get", "put", "stat", "rm", "ls", "cp"])
    p.add_argument("bucket")
    p.add_argument("key", nargs="?", default="")
    p.add_argument("dst_key", nargs="?", default="", help="cp destination key")
    p.add_argument("-I", "--input", default="")
    p.add_argument("-O", "--output", default="")
    p.add_argument("--endpoint", default="http://127.0.0.1:65004",
                   help="object gateway endpoint")
    return p


async def run(args: argparse.Namespace) -> int:
    store = Dfstore(args.endpoint)
    try:
        if args.op == "get":
            n = await store.get_object(args.bucket, args.key, args.output)
            print(json.dumps({"bytes": n, "output": args.output}))
        elif args.op == "put":
            await store.put_object(args.bucket, args.key, args.input)
            print(json.dumps({"stored": args.key}))
        elif args.op == "stat":
            size = await store.is_object_exist(args.bucket, args.key)
            if size is None:
                print(json.dumps({"exists": False}))
                return 1
            print(json.dumps({"exists": True, "size": size}))
        elif args.op == "rm":
            await store.delete_object(args.bucket, args.key)
            print(json.dumps({"deleted": args.key}))
        elif args.op == "ls":
            print(json.dumps(await store.list_objects(args.bucket)))
        elif args.op == "cp":
            await store.copy_object(args.bucket, args.key, args.dst_key)
            print(json.dumps({"copied": [args.key, args.dst_key]}))
        return 0
    except Exception as exc:  # noqa: BLE001 - CLI boundary
        print(f"dfstore: {exc}", file=sys.stderr)
        return 1


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    raise SystemExit(main())
