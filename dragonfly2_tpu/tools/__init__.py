"""CLI tools: dfget (download), dfcache (P2P cache ops), dfstore (object
gateway client), plus service launchers. Role parity: reference ``cmd/``."""
