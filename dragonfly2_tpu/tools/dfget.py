"""dfget: download a URL through the P2P fabric.

Role parity: reference ``cmd/dfget`` + ``client/dfget/dfget.go`` —
``Download`` via the daemon's local socket, daemon spawn-on-demand, and the
direct-from-source fallback with digest check; recursive directory download
(BFS over the source lister).

Usage:
    python -m dragonfly2_tpu.tools.dfget URL -O /path/out [options]
"""

from __future__ import annotations

import argparse
import asyncio
import os
import subprocess
import sys
import time

from ..common import digest as digestlib
from ..common.dfpath import DFPath
from ..common.errors import Code, DFError
from ..common.unit import format_bytes
from ..idl.messages import DownloadRequest, Empty, UrlMeta
from ..rpc.client import Channel, ServiceClient


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dfget", description="P2P-accelerated download")
    p.add_argument("url", help="source URL (http/https/file/gs/memory)")
    p.add_argument("-O", "--output", required=True, help="output path")
    p.add_argument("--digest", default="", help="expected digest algo:hex")
    p.add_argument("--tag", default="", help="task isolation tag")
    p.add_argument("--application", default="")
    p.add_argument("--priority", type=int, default=0, choices=range(7),
                   help="download priority LEVEL0 (highest) .. LEVEL6; "
                   "0 also means 'resolve via the application table'")
    p.add_argument("--tenant", default="",
                   help="tenant this download is accounted to "
                   "(quotas, per-tenant QoS attribution)")
    p.add_argument("--qos-class", default="", dest="qos_class",
                   choices=("", "critical", "standard", "bulk"),
                   help="QoS service class: critical (latency-sensitive "
                   "foreground), standard (default), bulk (background — "
                   "throttled/queued/shed first under brownout)")
    p.add_argument("--shards", default="",
                   help="sharded tasks: comma-joined manifest shard names "
                   "THIS host needs (requires --shard-manifest); only the "
                   "pieces covering them are pulled and the output file is "
                   "sparse outside them")
    p.add_argument("--shard-manifest", default="", dest="shard_manifest",
                   help="path to a shard-manifest JSON file ({\"shards\": "
                   "[{name, range_start, range_size, dtype?, shape?, "
                   "digest?}, ...]}); per-shard ready timestamps are "
                   "printed as shards verify")
    p.add_argument("--header", action="append", default=[],
                   help="extra origin header K:V (repeatable)")
    p.add_argument("--filter", action="append", default=[],
                   help="query params excluded from the task id (repeatable)")
    p.add_argument("--range", dest="range_", default="", help="bytes=a-b sub-range")
    p.add_argument("--timeout", type=float, default=0.0)
    p.add_argument("--daemon-sock", default="", help="daemon unix socket path")
    p.add_argument("--no-daemon", action="store_true",
                   help="skip daemon; fetch straight from the source")
    p.add_argument("--spawn-daemon", action="store_true",
                   help="start a daemon if the socket is dead")
    p.add_argument("--recursive", "-r", action="store_true")
    p.add_argument("--quiet", "-q", action="store_true")
    return p


def _meta(args) -> UrlMeta:
    header = {}
    for h in args.header:
        k, _, v = h.partition(":")
        header[k.strip()] = v.strip()
    from ..idl.messages import Priority
    return UrlMeta(digest=args.digest, tag=args.tag, range=args.range_,
                   application=args.application, header=header or None,
                   filtered_query_params=args.filter or None,
                   priority=Priority(args.priority),
                   tenant=getattr(args, "tenant", ""),
                   qos_class=getattr(args, "qos_class", ""),
                   shards=getattr(args, "shards", ""))


def _load_shard_manifest(path: str):
    """Parse a shard-manifest JSON file into the wire ShardManifest.
    Accepts ``{"shards": [...]}`` or a bare list of shard objects."""
    if not path:
        return None
    import json

    from ..idl.messages import ShardInfo, ShardManifest

    # dflint: disable=DF001 — one KB-scale manifest read on dfget's CLI-private loop
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    entries = raw.get("shards", raw) if isinstance(raw, dict) else raw
    shards = [ShardInfo(name=e["name"],
                        range_start=int(e["range_start"]),
                        range_size=int(e["range_size"]),
                        dtype=e.get("dtype", "uint8"),
                        shape=list(e["shape"]) if e.get("shape") else None,
                        digest=e.get("digest", ""))
              for e in entries]
    return ShardManifest(shards=shards)


async def _daemon_alive(sock: str) -> bool:
    # dflint: disable=DF001 — one stat on dfget's CLI-private loop
    if not os.path.exists(sock):
        return False
    ch = Channel(f"unix:{sock}")
    try:
        health = ServiceClient(ch, "df.health.Health", max_attempts=1)
        await asyncio.wait_for(health.unary("Check", Empty()), 2.0)
        return True
    except Exception:  # noqa: BLE001
        return False
    finally:
        await ch.close()


def _spawn_daemon(sock: str) -> None:
    """Start a detached daemon process bound to ``sock``."""
    # dflint: disable=DF001 — detached daemon bootstrap from the CLI; spawn latency IS the UX here
    subprocess.Popen(
        [sys.executable, "-m", "dragonfly2_tpu.tools.daemon",
         "--unix-sock", sock],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)


async def download_via_daemon(sock: str, args, *, progress=None) -> None:
    ch = Channel(f"unix:{sock}")
    t0 = time.monotonic()
    try:
        client = ServiceClient(ch, "df.daemon.Daemon")
        req = DownloadRequest(url=args.url, output=os.path.abspath(args.output),
                              url_meta=_meta(args), timeout_s=args.timeout,
                              recursive=args.recursive,
                              shard_manifest=_load_shard_manifest(
                                  getattr(args, "shard_manifest", "")))
        if args.recursive:
            # concurrent per-file events interleave on one stream with no
            # file identity on progress frames — a single-file percentage
            # renderer would garble them; report completed files instead
            files = 0
            total = 0
            async for resp in client.unary_stream("Download", req):
                if resp.done:
                    files += 1
                    total += resp.completed_length
                    if not args.quiet:
                        print(f"dfget: [{files}] {resp.output} "
                              f"({format_bytes(resp.completed_length)})")
            if not args.quiet:
                print(f"dfget: {files} files, {format_bytes(total)} total")
            return
        async for resp in client.unary_stream("Download", req):
            if resp.shard and not args.quiet:
                # per-shard ready timestamp: the shard's bytes all
                # verified (and its HBM handoff is enqueued when a device
                # sink rides the request) — the time-to-serving series
                print(f"\rdfget: shard {resp.shard} ready "
                      f"[{resp.shards_ready}/{resp.shards_total}] "
                      f"({resp.shard_src}) at "
                      f"{time.monotonic() - t0:.3f}s          ")
                continue
            if progress and not resp.done:
                progress(resp.completed_length, resp.content_length)
            if resp.done and progress:
                progress(resp.completed_length, resp.content_length, done=True)
    finally:
        await ch.close()


async def download_from_source(args, *, progress=None) -> None:
    """Direct origin fetch (no daemon): the reference's ``downloadFromSource``
    fallback, with digest verification. ``--recursive`` BFS-mirrors the
    listing client-side exactly like the reference's ``recursiveDownload``
    (``client/dfget/dfget.go:317``)."""
    from ..source import SourceRequest, client_for

    client = client_for(args.url)
    try:
        if getattr(args, "recursive", False):
            await _recursive_from_source(client, args, progress)
        else:
            req = SourceRequest(url=args.url, timeout_s=args.timeout)
            await _download_from_source_inner(client, req, args, progress)
    finally:
        close = getattr(client, "close", None)
        if close is not None:
            await close()


async def _recursive_from_source(client, args, progress) -> None:
    import copy

    from ..source import SourceRequest
    from ..source.client import walk

    meta = _meta(args)
    header = dict(meta.header) if meta.header else None
    async for e, rel in walk(args.url, timeout_s=args.timeout, header=header):
        sub = copy.copy(args)
        sub.url = e.url
        sub.output = os.path.join(args.output, rel)
        sub.digest = ""    # a whole-tree digest can't apply per file
        sub.range_ = ""
        await _download_from_source_inner(
            client, SourceRequest(url=e.url, header=dict(header or {}),
                                  timeout_s=args.timeout),
            sub, progress)


async def _download_from_source_inner(client, req, args, progress) -> None:
    from ..common.piece import parse_http_range
    from ..source import SourceRequest

    if args.range_:
        total = await client.content_length(SourceRequest(url=args.url))
        req.range = parse_http_range(args.range_, total)
    resp = await client.download(req)
    tmp = args.output + ".dfget.tmp"
    # dflint: disable=DF001 — daemon-less fallback on dfget's CLI-private loop; blocking it slows only this invocation
    os.makedirs(os.path.dirname(os.path.abspath(tmp)) or ".", exist_ok=True)
    hasher = None
    algo = want = ""
    if args.digest:
        algo, want = digestlib.parse(args.digest)
        hasher = digestlib.Hasher(algo)
    done = 0
    # dflint: disable=DF001 — CLI-private loop, see above
    with open(tmp, "wb") as f:
        assert resp.chunks is not None
        async for chunk in resp.chunks:
            # dflint: disable=DF001 — CLI-private loop, see above
            f.write(chunk)
            done += len(chunk)
            if hasher is not None:
                hasher.update(chunk)
            if progress:
                progress(done, resp.content_length)
    if hasher is not None:
        got = hasher.hexdigest()
        if got != want:
            # dflint: disable=DF001 — CLI-private loop, see above
            os.unlink(tmp)
            raise DFError(Code.CLIENT_DIGEST_MISMATCH,
                          f"digest mismatch from source: {algo}:{got[:12]}..")
    # dflint: disable=DF001 — CLI-private loop, see above
    os.replace(tmp, args.output)
    if progress:
        progress(done, done, done=True)


async def run(args) -> int:
    t0 = time.monotonic()
    last: dict = {"len": 0}

    def progress(completed: int, total: int, done: bool = False) -> None:
        if args.quiet:
            return
        last["len"] = completed
        if done:
            dt = time.monotonic() - t0
            rate = completed / dt if dt > 0 else 0
            print(f"\rdfget: {format_bytes(completed)} in {dt:.2f}s "
                  f"({format_bytes(rate)}/s)          ")
        else:
            pct = f"{100 * completed / total:5.1f}%" if total > 0 else "   ?  "
            print(f"\rdfget: {pct} {format_bytes(completed)}", end="", flush=True)

    if args.no_daemon:
        await download_from_source(args, progress=progress)
        return 0
    sock = args.daemon_sock or DFPath().daemon_sock()
    if not await _daemon_alive(sock):
        if args.spawn_daemon:
            _spawn_daemon(sock)
            for _ in range(50):
                await asyncio.sleep(0.2)
                if await _daemon_alive(sock):
                    break
            else:
                print("dfget: daemon did not come up; falling back to source",
                      file=sys.stderr)
                await download_from_source(args, progress=progress)
                return 0
        else:
            await download_from_source(args, progress=progress)
            return 0
    await download_via_daemon(sock, args, progress=progress)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.shards and not args.shard_manifest:
        # without the manifest the daemon cannot map names to byte
        # ranges — silently downloading the whole checkpoint would be
        # exactly what the flag exists to avoid
        parser.error("--shards requires --shard-manifest (the daemon "
                     "needs the shard table to subset the download)")
    try:
        return asyncio.run(run(args))
    except DFError as exc:
        print(f"dfget: error: {exc.code.name}: {exc.message}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 130


if __name__ == "__main__":
    sys.exit(main())
