"""benchtrend: one table over every committed BENCH_pr*.json.

The perf trajectory of this tree is a stack of per-PR dfbench
artifacts — each one self-contained, none of them comparable at a
glance. This tool folds them into a single table: one row per
artifact, its headline metric(s), and whether its baseline
``schedule_digest`` still matches BENCH_pr3 (the byte-identical
purity spine every observer PR gates on).

Usage:
    python -m dragonfly2_tpu.tools.benchtrend [--dir REPO] [--json]

Pure functions over the JSON files — tier-1 tests drive ``collect``
directly to assert every committed artifact still parses and every
digest gate still references pr3.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_PR_RE = re.compile(r"BENCH_pr(\d+)\.json$")


def _headline(pr: int, d: dict) -> str:
    """One human line per artifact: the number the PR existed to move.
    Defensive: a key that moved in a later PR degrades to '?', never a
    crash — benchtrend must render the whole trajectory even when one
    artifact's schema drifted."""
    try:
        if pr == 3:
            return (f"{d.get('daemons')}d x {d.get('pieces')}p baseline, "
                    f"seed_served={d.get('seed_served_ratio', '?')}, "
                    f"makespan={d.get('wall_ms', '?')}ms")
        if pr == 4:
            r = d.get("p2p_served_ratio") or {}
            return ("scheds-down p2p ratio: " + ", ".join(
                f"{k}={v}" for k, v in sorted(r.items())))
        if pr == 5:
            imp = d.get("improvement") or {}
            lag = imp.get("max_loop_lag_ms") or {}
            return (f"max loop lag legacy={lag.get('legacy', '?')}ms vs "
                    f"zero_stall={lag.get('zero_stall', '?')}ms")
        if pr == 6:
            amp = d.get("amplification") or {}
            bn = d.get("baseline_bottleneck") or {}
            return (f"amplification baseline="
                    f"{amp.get('baseline', '?')} vs no_pex="
                    f"{amp.get('scheds_down_no_pex', '?')}, bottleneck "
                    f"{bn.get('src', '?')}->{bn.get('dst', '?')}")
        if pr == 8:
            return (f"{d.get('decision_rows', '?')} decision rows, "
                    f"ledger_pure={d.get('ledger_pure', '?')}")
        if pr == 9:
            g = (d.get("growth_factor") or {}).get("cold_relay", "?")
            return f"cold relay makespan growth x{g}"
        if pr == 10:
            return (f"origin after epoch0 "
                    f"{d.get('origin_bytes_after_first_epoch', '?')} B, "
                    f"alias_zero={d.get('alias_pull_zero_transfer', '?')}")
        if pr == 11:
            return (f"fg p99 ratio qos={d.get('fg_p99_ratio_qos', '?')}x "
                    f"vs no_qos={d.get('fg_p99_ratio_no_qos', '?')}x, "
                    f"holds_slo={d.get('fg_holds_slo', '?')}")
        if pr == 12:
            w = d.get("wasted_ratio") or {}
            return (f"wasted on={w.get('on', '?')} off={w.get('off', '?')}, "
                    f"pure={d.get('quarantine_pure', '?')}")
        if pr == 13:
            oc = (d.get("origin_copies") or {}).get("fed_hier") or {}
            return (f"hier_beats_naive={d.get('hier_beats_naive', '?')}, "
                    f"origin copies "
                    f"{oc.get(max(oc, default=''), '?') if oc else '?'}")
        if pr == 14:
            return (f"sharded speedup={d.get('speedup', '?')}x"
                    f"@{d.get('speedup_size', '?')}, "
                    f"tree_bounded={d.get('tree_bounded', '?')}")
        if pr == 16:
            rps = d.get("rulings_per_sec") or {}
            big = str((d.get("fleets") or ["?"])[-1])
            return (f"{rps.get(big, '?')}/s rulings @ {big}d, "
                    f"pure={d.get('profiler_pure', '?')}"
                    f"/{d.get('ctrl_profiler_pure', '?')}")
        if pr == 17:
            oh = d.get("origin_hits_after_restart") or {}
            return (f"origin hits durable={oh.get('durable', '?')} vs "
                    f"amnesia={oh.get('amnesia', '?')}, "
                    f"sticky={d.get('affinity_sticky', '?')}")
        if pr == 18:
            lat = d.get("detection_latency_intervals") or {}
            return (f"{len(d.get('detected_kinds') or [])}/6 kinds, "
                    f"worst latency "
                    f"{max(lat.values(), default='?')} intervals, "
                    f"fp={sum((d.get('false_positives') or {}).values())}, "
                    f"{d.get('bytes_per_announce', '?')} B/announce")
        if pr == 19:
            reg = d.get("regret") or {}
            return (f"regret learned={reg.get('learned', '?')} vs "
                    f"heuristic={reg.get('heuristic', '?')}, "
                    f"flip={d.get('flip_rate', '?')}, "
                    f"beats={d.get('learned_beats_heuristic', '?')}, "
                    f"deterministic={d.get('trained_deterministic', '?')}"
                    f"/{d.get('learned_deterministic', '?')}")
    except Exception:  # noqa: BLE001 - schema drift degrades, never crashes
        pass
    return "?"


def collect(repo_dir: str) -> list[dict]:
    """One row per BENCH_pr*.json, ordered by PR number. ``digest_vs_pr3``
    is True/False when the artifact carries a top-level
    ``schedule_digest`` (the purity spine), None when the bench predates
    or has no baseline leg. Raises on unparseable JSON — a torn
    committed artifact IS the finding."""
    files = sorted(glob.glob(os.path.join(repo_dir, "BENCH_pr*.json")),
                   key=lambda p: int(_PR_RE.search(p).group(1)))
    pr3_digest = ""
    rows = []
    for path in files:
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        pr = int(_PR_RE.search(path).group(1))
        digest = d.get("schedule_digest") or ""
        if pr == 3:
            pr3_digest = digest
        rows.append({
            "pr": pr,
            "file": os.path.basename(path),
            "bench": d.get("bench") or "?",
            "headline": _headline(pr, d),
            "schedule_digest": digest,
            "digest_vs_pr3": (None if not digest or not pr3_digest
                              else digest == pr3_digest),
        })
    # files sort by PR already, but pr3 must have been seen before any
    # comparison — it is the lowest committed PR number by construction
    for r in rows:
        if r["schedule_digest"] and pr3_digest:
            r["digest_vs_pr3"] = r["schedule_digest"] == pr3_digest
    return rows


def render(rows: list[dict]) -> str:
    out = [f"{'pr':>4} {'bench':<20} {'=pr3':<5} headline"]
    for r in rows:
        gate = {True: "ok", False: "DRIFT", None: "-"}[r["digest_vs_pr3"]]
        out.append(f"{r['pr']:>4} {r['bench']:<20} {gate:<5} "
                   f"{r['headline']}")
    drift = [r["file"] for r in rows if r["digest_vs_pr3"] is False]
    out.append(f"{len(rows)} artifacts; "
               + (f"DIGEST DRIFT: {', '.join(drift)}" if drift
                  else "all digest gates reference pr3"))
    return "\n".join(out)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="benchtrend",
        description="fold every committed BENCH_pr*.json into one "
                    "perf-trajectory table")
    p.add_argument("--dir", default=".",
                   help="repo root holding the BENCH_pr*.json artifacts")
    p.add_argument("--json", action="store_true",
                   help="machine-readable rows instead of the table")
    args = p.parse_args(argv)
    try:
        rows = collect(args.dir)
    except (OSError, ValueError) as exc:
        print(f"benchtrend: {type(exc).__name__}: {exc}", file=sys.stderr)
        return 1
    if not rows:
        print(f"benchtrend: no BENCH_pr*.json under {args.dir}",
              file=sys.stderr)
        return 1
    print(json.dumps(rows, indent=2) if args.json else render(rows))
    # a committed artifact whose baseline digest drifted off pr3 is a
    # broken purity gate — exit non-zero so CI can hang the run on it
    return 2 if any(r["digest_vs_pr3"] is False for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
