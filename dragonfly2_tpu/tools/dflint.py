"""dflint — this fabric's static concurrency-and-resource analyzer.

Usage::

    python -m dragonfly2_tpu.tools.dflint [--json] [--stats] [--changed] [paths…]

With no paths, lints the whole ``dragonfly2_tpu`` package with the
two-pass interprocedural engine: an index pass builds package-wide
symbol tables and per-function summaries, and the analysis pass resolves
call edges across module boundaries (see docs/ANALYSIS.md, "Engine").
``--changed`` lints only files differing from the git merge-base with
upstream (fast pre-commit mode). ``--json`` emits machine-readable
findings, including every suppression and its mandatory reason.
``--stats`` emits per-rule finding counts, per-pass wall time, and
per-module cache hit/miss counts. Exit status: 0 clean (or
suppressed-only), 1 unsuppressed findings, 2 usage/IO error.

Rules live in ``dragonfly2_tpu.tools.dflint_rules`` — one per hazard
class this repo has actually hit (see docs/ANALYSIS.md for the
catalogue and the incident behind each rule). The tier-1 gate
(tests/test_dflint.py) runs this over the package and fails on any
unsuppressed finding, so concurrency discipline is enforced
mechanically rather than by reviewer memory.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from .dflint_rules import Finding, lint_paths

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)


def _git(args: list[str]) -> str | None:
    try:
        out = subprocess.run(["git", *args], cwd=REPO_ROOT,
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout.strip() if out.returncode == 0 else None


def changed_files(git=_git) -> list[str]:
    """Package python files differing from the **merge-base** with
    upstream — the cheap pre-commit surface, scoped to what the tier-1
    gate enforces (tests legitimately block their private loops).

    The changed set is one ``git diff <merge-base>`` against the working
    tree: that covers both branch-local commits (so CI on a feature
    branch lints everything the branch touched, not just dirty files)
    and uncommitted edits. The index (``--cached``) is deliberately NOT
    consulted — staging state is a laptop-local artifact CI doesn't
    have, and diffing it scoped branches wrong. Falls back through
    origin/main to plain HEAD when no upstream exists. Untracked files
    are unioned in: brand-new files never appear in ``git diff`` and are
    exactly the files most likely to carry fresh hazards.

    ``git`` is injectable for tests."""
    base = None
    for ref in ("@{upstream}", "origin/main", "origin/master"):
        base = git(["merge-base", "HEAD", ref])
        if base:
            break
    diff = git(["diff", "--name-only", base or "HEAD", "--",
                "*.py"]) or ""
    untracked = git(["ls-files", "--others", "--exclude-standard",
                     "--", "*.py"]) or ""
    diff = diff + "\n" + untracked
    out = []
    for rel in dict.fromkeys(ln for ln in diff.splitlines() if ln.strip()):
        path = os.path.join(REPO_ROOT, rel)
        if (os.path.exists(path) and rel.endswith(".py")
                and os.path.abspath(path).startswith(PKG_ROOT + os.sep)):
            out.append(path)
    return out


def run(paths: list[str], *, as_json: bool = False, with_stats: bool = False,
        out=sys.stdout) -> int:
    stats: dict = {}
    findings = lint_paths(paths, repo_root=REPO_ROOT, stats=stats)
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if with_stats:
        # the CI-facing shape: per-rule counts + per-pass wall time, so
        # the gate's own latency is observable and regression-gateable
        json.dump({
            "counts": {"findings": len(active),
                       "suppressed": len(suppressed),
                       "by_code": _by_code(active),
                       "by_code_suppressed": _by_code(suppressed)},
            "passes": {"index_s": stats.get("index_s", 0.0),
                       "analysis_s": stats.get("analysis_s", 0.0)},
            "cache": {"hits": stats.get("cache_hits", 0),
                      "misses": stats.get("cache_misses", 0)},
            "files": stats.get("files", 0),
            "modules_indexed": stats.get("modules_indexed", 0),
        }, out, indent=2)
        out.write("\n")
    elif as_json:
        json.dump({
            "findings": [f.as_dict() for f in active],
            "suppressed": [f.as_dict() for f in suppressed],
            "counts": {"findings": len(active),
                       "suppressed": len(suppressed),
                       "by_code": _by_code(active)},
        }, out, indent=2)
        out.write("\n")
    else:
        # text mode prints only real findings — 50+ justified
        # suppressions would bury the one line that matters; the full
        # suppression ledger (with reasons) lives behind --json
        for f in active:
            print(f.render(), file=out)
        print(f"dflint: {len(active)} finding(s), "
              f"{len(suppressed)} suppressed", file=out)
    return 1 if active else 0


def _by_code(findings: list[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.code] = counts.get(f.code, 0) + 1
    return dict(sorted(counts.items()))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dflint", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint "
                         "(default: the dragonfly2_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output incl. suppressions")
    ap.add_argument("--stats", action="store_true", dest="with_stats",
                    help="JSON per-rule finding counts, per-pass wall "
                         "time, and cache hit/miss counts")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files differing from the git "
                         "merge-base with upstream")
    args = ap.parse_args(argv)

    if args.changed:
        paths = changed_files()
        if not paths:
            if not (args.as_json or args.with_stats):
                print("dflint: no changed python files")
                return 0
            # machine-readable modes keep their schema on the empty set
            # — a CI pipeline piping --stats to jq must not get prose
            # precisely on the branches with nothing to lint. One
            # schema definition: run() on the empty file list emits the
            # same all-zeros payload the non-empty path would
            return run([], as_json=args.as_json,
                       with_stats=args.with_stats)
    elif args.paths:
        paths = [os.path.abspath(p) for p in args.paths]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"dflint: no such path: {', '.join(missing)}",
                  file=sys.stderr)
            return 2
    else:
        paths = [PKG_ROOT]
    return run(paths, as_json=args.as_json, with_stats=args.with_stats)


if __name__ == "__main__":
    sys.exit(main())
