"""Trainer launcher: ``python -m dragonfly2_tpu.tools.trainer``.

Role parity: reference ``cmd/trainer`` (cobra launcher over
``trainer.New``/``Serve``).
"""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ..common import logging as dflog
from ..common.config import env_overrides, load_config
from ..trainer.server import Trainer, TrainerConfig


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="df-trainer")
    p.add_argument("--config", default="", help="YAML/JSON config file")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--listen-ip", default="")
    p.add_argument("--data-dir", default="")
    p.add_argument("--manager", action="append", default=[],
                   help="manager address (repeatable)")
    from ..common.debug_http import add_debug_arg
    add_debug_arg(p)
    p.add_argument("--verbose", "-v", action="store_true")
    return p


async def serve(cfg: TrainerConfig, debug_port: int = 0) -> None:
    from ..common import health
    health.PLANE.acquire()   # loop watchdog + /debug/health on --debug-port
    trainer = Trainer(cfg)
    await trainer.start()
    from ..common.debug_http import maybe_start_debug
    debug_runner = await maybe_start_debug(debug_port)
    print(f"trainer up: {trainer.address}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if debug_runner is not None:
        await debug_runner.cleanup()
    await trainer.stop()
    health.PLANE.release()
    from ..common import tracing
    # the OTLP drain sleeps in bounded 50 ms hops — off-loop, so a
    # still-draining RPC server isn't parked behind the span flush
    await asyncio.to_thread(tracing.shutdown)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    dflog.setup("DEBUG" if args.verbose else "INFO")
    overrides: dict = env_overrides()
    if args.port:
        overrides["port"] = args.port
    if args.listen_ip:
        overrides["listen_ip"] = args.listen_ip
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    if args.manager:
        overrides["manager_addresses"] = args.manager
    cfg = load_config(TrainerConfig, args.config or None, overrides)
    asyncio.run(serve(cfg, debug_port=args.debug_port))
    return 0


if __name__ == "__main__":
    sys.exit(main())
