"""dflint rule engine: one registry, one walker, one output format.

dflint is this fabric's project-specific static analyzer. Every rule is
distilled from a real post-mortem in this repo (the incident lives in the
rule's docstring), because three of the first six PRs each burned a
debugging cycle on the *same class* of asyncio bug: a lost ``wait_for``
cancellation (PR 1), a cross-task ``wait_for(cond.wait(), t)`` lock leak
that deadlocked the pod with zero log output (PR 2), and event-loop
starvation from per-byte CPU on the loop thread (PR 5). The daemon runs
ONE event loop; anything that blocks it caps feeder throughput for every
task in the process, which is exactly the core-bound bottleneck the
concurrency-limits literature (PAPERS.md) identifies.

Suppression grammar (the reason is MANDATORY and surfaced in ``--json``)::

    some_call()  # dflint: disable=DF001 — tiny /proc read, not worth a hop

A suppression comment applies to findings on its own line or on the line
directly below it (banner form).  A ``# dflint:`` comment that does not
parse — unknown code, missing reason — is itself a finding (DF000) so a
suppression can never silently rot.

See docs/ANALYSIS.md for the rule catalogue.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Iterator

__all__ = [
    "Finding", "Suppression", "ModuleCtx", "Rule", "RULES",
    "lint_source", "lint_file", "lint_paths",
]

_SUPPRESS_RE = re.compile(
    r"#\s*dflint:\s*disable=(?P<codes>DF\d{3}(?:\s*,\s*DF\d{3})*)"
    r"\s*(?:—|–|--+|-)\s*(?P<reason>\S.*?)\s*$")
_MENTION_RE = re.compile(r"#\s*dflint\s*:")


@dataclass
class Suppression:
    """One parsed ``# dflint: disable=…`` comment."""
    codes: tuple[str, ...]
    reason: str
    line: int
    used: bool = False


@dataclass
class Finding:
    code: str
    path: str           # repo-relative when under repo_root
    line: int
    col: int
    message: str
    suppression: Suppression | None = None

    @property
    def suppressed(self) -> bool:
        return self.suppression is not None

    def as_dict(self) -> dict:
        d = {"code": self.code, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message}
        if self.suppression is not None:
            d["suppressed"] = True
            d["reason"] = self.suppression.reason
        return d

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.suppression.reason \
            if self.suppression else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.code} {self.message}{tag}")


@dataclass
class ModuleCtx:
    """Everything a rule may need about one module under analysis."""
    path: str                   # absolute
    rel: str                    # repo-relative (display + scoping)
    src: str
    tree: ast.Module
    repo_root: str
    # cross-file caches shared by every module of one lint run (docs
    # text, package-wide faultgate fire sites, …) — see catalogue rules
    project: dict = field(default_factory=dict)


class Rule:
    """Base class: subclass, set ``code``/``name``, implement ``check``.

    The class docstring of each concrete rule carries the incident that
    motivates it — dflint rules are post-mortems made executable, and the
    docstring is the part a developer reads when the rule fires on them.
    """

    code: str = "DF000"
    name: str = "base"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


#: The one registry. Populated by the rule modules at import time below.
RULES: list[Rule] = []


def register(rule_cls: type[Rule]) -> type[Rule]:
    RULES.append(rule_cls())
    return rule_cls


# ---------------------------------------------------------------------------
# suppression scanning
# ---------------------------------------------------------------------------

def scan_suppressions(src: str, rel: str) -> tuple[list[Suppression],
                                                   list[Finding]]:
    """Parse every ``# dflint:`` comment; malformed ones become DF000
    findings (a suppression with no reason is itself a violation — the
    reason is the suppression's audit trail)."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.start[1], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [], []
    for line, col, text in comments:
        if not _MENTION_RE.search(text):
            continue
        m = _SUPPRESS_RE.search(text)
        if m is None:
            bad.append(Finding(
                "DF000", rel, line, col,
                "malformed dflint suppression — grammar is "
                "`# dflint: disable=DF00X — <reason>` and the reason "
                "is mandatory"))
            continue
        codes = tuple(c.strip() for c in m.group("codes").split(","))
        sups.append(Suppression(codes, m.group("reason"), line))
    return sups, bad


def _apply_suppressions(findings: list[Finding], sups: list[Suppression],
                        rel: str) -> None:
    by_line: dict[int, list[Suppression]] = {}
    for s in sups:
        by_line.setdefault(s.line, []).append(s)
    for f in findings:
        if f.code == "DF000":
            continue        # the suppression police cannot be suppressed
        for line in (f.line, f.line - 1):
            done = False
            for s in by_line.get(line, ()):
                if f.code in s.codes:
                    f.suppression = s
                    s.used = True
                    done = True
                    break
            if done:
                break
    # a suppression that matches nothing is rot: the hazard it excused
    # was fixed or moved, and leaving it in place would silently excuse
    # the NEXT finding introduced on that line
    for s in sups:
        if not s.used:
            findings.append(Finding(
                "DF000", rel, s.line, 0,
                f"unused suppression for {', '.join(s.codes)} — no "
                f"matching finding on this or the next line; remove it "
                f"(a stale disable would mask the next real hazard here)"))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_source(src: str, path: str, *, repo_root: str | None = None,
                project: dict | None = None) -> list[Finding]:
    """Lint one module's source text. Returns ALL findings, suppressed
    ones included (marked); callers filter on ``.suppressed``."""
    root = os.path.abspath(repo_root or os.getcwd())
    apath = os.path.abspath(path)
    rel = os.path.relpath(apath, root) if apath.startswith(root) else path
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [Finding("DF000", rel, exc.lineno or 1, exc.offset or 0,
                        f"syntax error, file not analyzed: {exc.msg}")]
    ctx = ModuleCtx(path=apath, rel=rel, src=src, tree=tree,
                    repo_root=root,
                    project=project if project is not None else {})
    sups, bad = scan_suppressions(src, rel)
    findings: list[Finding] = list(bad)
    for rule in RULES:
        findings.extend(rule.check(ctx))
    _apply_suppressions(findings, sups, rel)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_file(path: str, *, repo_root: str | None = None,
              project: dict | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path, repo_root=repo_root, project=project)


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        elif p.endswith(".py"):
            yield p


def lint_paths(paths: Iterable[str], *,
               repo_root: str | None = None) -> list[Finding]:
    """Lint every ``.py`` under the given files/directories with one
    shared project cache (docs are read once per run, not per file)."""
    project: dict = {}
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path, repo_root=repo_root,
                                  project=project))
    return findings


# rule modules self-register on import — keep these at the bottom so the
# registry and helpers above exist when they do
from . import concurrency  # noqa: E402,F401
from . import catalogue    # noqa: E402,F401
