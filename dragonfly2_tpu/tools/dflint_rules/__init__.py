"""dflint rule engine: one registry, one walker, one output format.

dflint is this fabric's project-specific static analyzer. Every rule is
distilled from a real post-mortem in this repo (the incident lives in the
rule's docstring), because three of the first six PRs each burned a
debugging cycle on the *same class* of asyncio bug: a lost ``wait_for``
cancellation (PR 1), a cross-task ``wait_for(cond.wait(), t)`` lock leak
that deadlocked the pod with zero log output (PR 2), and event-loop
starvation from per-byte CPU on the loop thread (PR 5). The daemon runs
ONE event loop; anything that blocks it caps feeder throughput for every
task in the process, which is exactly the core-bound bottleneck the
concurrency-limits literature (PAPERS.md) identifies.

v2 (this engine) is **two-pass and package-wide**: pass 1 builds a
``symbols.PackageIndex`` (per-module symbol tables, imports resolved
within the package, per-function summaries at fixpoint), pass 2 runs the
rules with call sites resolved against the index — so DF001/DF005 follow
calls through ``common/``/``storage/``/``daemon/``/``scheduler/``
boundaries instead of going blind at each ``import``, and the DF007–9
dataflow families can reason about resources that cross modules. Module
rules are cached per module, keyed by content hash + the interface
digest of every imported module (see ``interface_digest``): an edit
re-analyzes only the touched module and the dependents whose *observable
interface* actually moved, which is what keeps the tier-1 gate fast.
Rules that need the whole graph at once (the DF009 lock-ordering cycle
check) register as GLOBAL_RULES and re-run every time — the graph walk
is cheap once the summaries exist.

Suppression grammar (the reason is MANDATORY and surfaced in ``--json``)::

    some_call()  # dflint: disable=DF001 — tiny /proc read, not worth a hop

A suppression comment applies to findings on its own line or on the line
directly below it (banner form).  A ``# dflint:`` comment that does not
parse — unknown code, missing reason — is itself a finding (DF000) so a
suppression can never silently rot.

See docs/ANALYSIS.md for the rule catalogue and the engine design.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Iterator

from .symbols import (ModuleIndex, PackageIndex, SUPPRESS_RE,
                      package_root_for)

__all__ = [
    "Finding", "Suppression", "ModuleCtx", "Rule", "RULES", "GLOBAL_RULES",
    "lint_source", "lint_file", "lint_paths",
]

#: bump when rule semantics change — invalidates every cache entry
ENGINE_VERSION = "2.1"
CACHE_NAME = ".dflint_cache.json"

# the one suppression grammar, shared with the index pass (symbols.py)
_SUPPRESS_RE = SUPPRESS_RE
_MENTION_RE = re.compile(r"#\s*dflint\s*:")

#: modules whose rules sweep the whole package themselves (faultgate
#: fire sites, priority-class surfaces) — their findings depend on files
#: the import graph doesn't see, so they are never served from cache
_NEVER_CACHE = ("common/faultgate.py", "idl/messages.py")


@dataclass
class Suppression:
    """One parsed ``# dflint: disable=…`` comment."""
    codes: tuple[str, ...]
    reason: str
    line: int
    used: bool = False


@dataclass
class Finding:
    code: str
    path: str           # repo-relative when under repo_root
    line: int
    col: int
    message: str
    suppression: Suppression | None = None

    @property
    def suppressed(self) -> bool:
        return self.suppression is not None

    def as_dict(self) -> dict:
        d = {"code": self.code, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message}
        if self.suppression is not None:
            d["suppressed"] = True
            d["reason"] = self.suppression.reason
        return d

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.suppression.reason \
            if self.suppression else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.code} {self.message}{tag}")


@dataclass
class ModuleCtx:
    """Everything a rule may need about one module under analysis."""
    path: str                   # absolute
    rel: str                    # repo-relative (display + scoping)
    src: str
    tree: ast.Module
    repo_root: str
    # cross-file caches shared by every module of one lint run (docs
    # text, package-wide faultgate fire sites, …) — see catalogue rules
    project: dict = field(default_factory=dict)
    # pass-1 products: this module's symbol table and the package index
    # it belongs to (a solo index for standalone files) — what lets the
    # analysis pass resolve call edges across module boundaries
    mod: ModuleIndex | None = None
    index: PackageIndex | None = None


class Rule:
    """Base class: subclass, set ``code``/``name``, implement ``check``.

    The class docstring of each concrete rule carries the incident that
    motivates it — dflint rules are post-mortems made executable, and the
    docstring is the part a developer reads when the rule fires on them.
    """

    code: str = "DF000"
    name: str = "base"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError


class GlobalRule(Rule):
    """A rule that needs the whole package graph at once (lock-ordering
    cycles span modules, so no per-module pass can see them). Runs once
    per package index; findings are attributed to the module each edge
    site lives in, and only sites inside *analyzed* modules report."""

    def check_package(self, index: PackageIndex,
                      analyzed: dict[str, str],
                      ) -> Iterator[Finding]:  # pragma: no cover
        """``analyzed`` maps modname -> repo-relative display path for
        every module in this lint run's scope."""
        raise NotImplementedError


#: The registries. Populated by the rule modules at import time below.
RULES: list[Rule] = []
GLOBAL_RULES: list[GlobalRule] = []


def register(rule_cls: type[Rule]) -> type[Rule]:
    RULES.append(rule_cls())
    return rule_cls


def register_global(rule_cls: type[GlobalRule]) -> type[GlobalRule]:
    GLOBAL_RULES.append(rule_cls())
    return rule_cls


# ---------------------------------------------------------------------------
# suppression scanning
# ---------------------------------------------------------------------------

def scan_suppressions(src: str, rel: str) -> tuple[list[Suppression],
                                                   list[Finding]]:
    """Parse every ``# dflint:`` comment; malformed ones become DF000
    findings (a suppression with no reason is itself a violation — the
    reason is the suppression's audit trail)."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.start[1], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [], []
    for line, col, text in comments:
        if not _MENTION_RE.search(text):
            continue
        m = _SUPPRESS_RE.search(text)
        if m is None:
            bad.append(Finding(
                "DF000", rel, line, col,
                "malformed dflint suppression — grammar is "
                "`# dflint: disable=DF00X — <reason>` and the reason "
                "is mandatory"))
            continue
        codes = tuple(c.strip() for c in m.group("codes").split(","))
        sups.append(Suppression(codes, m.group("reason"), line))
    return sups, bad


def _apply_suppressions(findings: list[Finding], sups: list[Suppression],
                        rel: str,
                        summary_used: set[tuple[str, int]] = frozenset(),
                        ) -> None:
    by_line: dict[int, list[Suppression]] = {}
    for s in sups:
        by_line.setdefault(s.line, []).append(s)
    for f in findings:
        if f.code == "DF000":
            continue        # the suppression police cannot be suppressed
        for line in (f.line, f.line - 1):
            done = False
            for s in by_line.get(line, ()):
                if f.code in s.codes:
                    f.suppression = s
                    s.used = True
                    done = True
                    break
            if done:
                break
    # a definition-site suppression the index pass consumed (it retired
    # a hazard from a function's package-wide summary) is used even when
    # no module-local finding matched it
    for code, line in summary_used:
        for s_line in (line, line - 1):
            for s in by_line.get(s_line, ()):
                if code in s.codes:
                    s.used = True
    # a suppression that matches nothing is rot: the hazard it excused
    # was fixed or moved, and leaving it in place would silently excuse
    # the NEXT finding introduced on that line
    for s in sups:
        if not s.used:
            findings.append(Finding(
                "DF000", rel, s.line, 0,
                f"unused suppression for {', '.join(s.codes)} — no "
                f"matching finding on this or the next line; remove it "
                f"(a stale disable would mask the next real hazard here)"))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _rel_of(path: str, root: str) -> str:
    apath = os.path.abspath(path)
    return os.path.relpath(apath, root) if apath.startswith(root) else path


def _run_module_rules(ctx: ModuleCtx) -> list[Finding]:
    findings: list[Finding] = []
    for rule in RULES:
        findings.extend(rule.check(ctx))
    return findings


def lint_source(src: str, path: str, *, repo_root: str | None = None,
                project: dict | None = None) -> list[Finding]:
    """Lint one module's source text. Returns ALL findings, suppressed
    ones included (marked); callers filter on ``.suppressed``.

    This path indexes the module *solo* (imports resolve to nothing), so
    analysis is module-local — the behavior fixtures pin. Package-wide
    resolution happens in ``lint_paths``, which indexes the whole
    package a file belongs to before analyzing it."""
    root = os.path.abspath(repo_root or os.getcwd())
    apath = os.path.abspath(path)
    rel = _rel_of(apath, root)
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [Finding("DF000", rel, exc.lineno or 1, exc.offset or 0,
                        f"syntax error, file not analyzed: {exc.msg}")]
    index = PackageIndex.solo(apath, src, tree)
    mi = index.by_path[apath]
    ctx = ModuleCtx(path=apath, rel=rel, src=src, tree=tree,
                    repo_root=root,
                    project=project if project is not None else {},
                    mod=mi, index=index)
    sups, bad = scan_suppressions(src, rel)
    findings: list[Finding] = list(bad)
    findings.extend(_run_module_rules(ctx))
    for rule in GLOBAL_RULES:
        findings.extend(rule.check_package(index, {mi.modname: rel}))
    _apply_suppressions(findings, sups, rel, mi.summary_used)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_file(path: str, *, repo_root: str | None = None,
              project: dict | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    return lint_source(src, path, repo_root=repo_root, project=project)


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        elif p.endswith(".py"):
            yield p


# -- the per-module result cache --------------------------------------------

def _cache_salt(root: str) -> str:
    """Rule results also depend on the docs the catalogue rules diff
    against — fold them (and the engine version) into every key."""
    h = hashlib.sha256(ENGINE_VERSION.encode())
    for doc in ("OBSERVABILITY.md", "RESILIENCE.md"):
        try:
            with open(os.path.join(root, "docs", doc), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"absent")
    return h.hexdigest()


def _load_cache(root: str) -> dict:
    try:
        with open(os.path.join(root, CACHE_NAME), encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def _save_cache(root: str, cache: dict) -> None:
    try:
        with open(os.path.join(root, CACHE_NAME), "w",
                  encoding="utf-8") as f:
            json.dump(cache, f)
    except OSError:
        pass        # read-only checkout: the cache is an optimization


def _from_cache_entry(entry: dict, rel: str) -> list[Finding]:
    return [Finding(d["code"], rel, d["line"], d["col"], d["message"])
            for d in entry.get("f", ())]


def lint_paths(paths: Iterable[str], *,
               repo_root: str | None = None,
               stats: dict | None = None) -> list[Finding]:
    """Lint every ``.py`` under the given files/directories.

    Two passes: index every package the files belong to (symbol tables +
    summaries at fixpoint), then analyze each requested module against
    the index. Per-module results are served from ``.dflint_cache.json``
    when neither the module's content nor the interface of anything it
    imports has changed. ``stats``, when given, is filled with per-pass
    wall times and cache hit/miss counts (the ``--stats`` payload)."""
    root = os.path.abspath(repo_root or os.getcwd())
    files = list(dict.fromkeys(
        os.path.abspath(p) for p in iter_py_files(paths)))

    t0 = time.perf_counter()
    indexes: dict[str, PackageIndex] = {}
    pkg_of: dict[str, str | None] = {}
    for path in files:
        pr = package_root_for(path)
        pkg_of[path] = pr
        if pr is not None and pr not in indexes:
            indexes[pr] = PackageIndex(pr)
    t_index = time.perf_counter() - t0

    salt = _cache_salt(root)
    cache = _load_cache(root)
    next_cache: dict = {}
    hits = misses = 0
    project: dict = {}
    findings: list[Finding] = []
    # per-file raw findings + suppressions, finalized after global rules
    per_file: dict[str, tuple] = {}
    analyzed: dict[str, dict[str, str]] = {}    # pkg -> modname -> rel
    solo_mods: list[tuple[PackageIndex, str, str]] = []

    t1 = time.perf_counter()
    for path in files:
        rel = _rel_of(path, root)
        index = indexes.get(pkg_of[path]) if pkg_of[path] else None
        mi = index.by_path.get(path) if index is not None else None
        if mi is None and index is not None:
            index = None            # unparsable: fall through to solo
        if mi is None:
            try:
                with open(path, encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src, filename=path)
            except OSError:
                continue
            except SyntaxError as exc:
                findings.append(Finding(
                    "DF000", rel, exc.lineno or 1, exc.offset or 0,
                    f"syntax error, file not analyzed: {exc.msg}"))
                continue
            index = PackageIndex.solo(path, src, tree)
            mi = index.by_path[path]
            solo_mods.append((index, mi.modname, rel))
        else:
            analyzed.setdefault(pkg_of[path], {})[mi.modname] = rel
        sups, bad = scan_suppressions(mi.src, rel)
        key = rel.replace(os.sep, "/")
        entry = cache.get(key)
        surface = index.import_surface_digest(mi)
        cacheable = not key.endswith(_NEVER_CACHE)
        if (cacheable and entry is not None
                and entry.get("ch") == mi.content_hash
                and entry.get("ih") == surface
                and entry.get("salt") == salt):
            raw = _from_cache_entry(entry, rel)
            hits += 1
        else:
            ctx = ModuleCtx(path=path, rel=rel, src=mi.src, tree=mi.tree,
                            repo_root=root, project=project,
                            mod=mi, index=index)
            raw = _run_module_rules(ctx)
            misses += 1
        if cacheable:
            next_cache[key] = {
                "ch": mi.content_hash, "ih": surface, "salt": salt,
                "f": [{"code": f.code, "line": f.line, "col": f.col,
                       "message": f.message} for f in raw]}
        per_file[rel] = (raw, sups, bad, mi.summary_used)

    # global rules: once per package graph, cycle edges and all — their
    # findings land in the owning module's bucket so its suppressions
    # (and the DF000 unused-suppression audit) see them
    for pkg, mods in analyzed.items():
        for rule in GLOBAL_RULES:
            for f in rule.check_package(indexes[pkg], mods):
                if f.path in per_file:
                    per_file[f.path][0].append(f)
                else:
                    findings.append(f)
    # standalone files get the same global pass over their solo index
    # (lint_source already does this — the CLI must not disagree with
    # the library on a shipped rule). Runs AFTER the cache write above,
    # so global findings are never serialized into a cache entry.
    for solo_index, modname, rel in solo_mods:
        for rule in GLOBAL_RULES:
            for f in rule.check_package(solo_index, {modname: rel}):
                if f.path in per_file:
                    per_file[f.path][0].append(f)
                else:
                    findings.append(f)

    for rel, (raw, sups, bad, summary_used) in per_file.items():
        merged = raw + bad
        _apply_suppressions(merged, sups, rel, summary_used)
        findings.extend(merged)
    t_analysis = time.perf_counter() - t1

    # merge, don't replace: a scoped run (--changed, one file) must not
    # evict the full-package entries a gate run paid for — staleness is
    # already policed per entry by the ch/ih/salt key. Prune what merge
    # can't: entries for deleted/renamed files and absolute-path keys
    # (out-of-root lint targets), or the file grows across every branch
    # switch forever
    cache.update(next_cache)
    cache = {k: v for k, v in cache.items()
             if not os.path.isabs(k)
             and os.path.exists(os.path.join(root, k))}
    _save_cache(root, cache)
    if stats is not None:
        stats.update({
            "files": len(files),
            "modules_indexed": sum(len(ix.modules)
                                   for ix in indexes.values()),
            "index_s": round(t_index, 4),
            "analysis_s": round(t_analysis, 4),
            "cache_hits": hits,
            "cache_misses": misses,
        })
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# rule modules self-register on import — keep these at the bottom so the
# registry and helpers above exist when they do
from . import concurrency  # noqa: E402,F401
from . import catalogue    # noqa: E402,F401
from . import dataflow     # noqa: E402,F401
from . import lockgraph    # noqa: E402,F401
