"""DF007/DF008: resource-lifecycle dataflow — pooled buffers and
acquire/refund pairs.

These two families codify this repo's own resource post-mortems the way
DF001–DF005 codify its asyncio ones. They are *dataflow* rules: a value
acquired at one site must provably reach its paired release on every
path the function can take, including the exception paths — which is
exactly where both incident classes hid.

The analysis is deliberately structural, not a full CFG: a release
counts as exception-safe when it lives in a ``finally`` or an ``except``
handler covering the acquire; a straight-line release with an ``await``
(a suspension point — and in this codebase every await can raise) or an
explicit ``raise`` in between is flagged. That approximation has no
false negatives on the shapes this repo has shipped and keeps the rule
readable; anything it over-flags takes a one-line reasoned suppression,
same as every other rule here.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import Finding, ModuleCtx, Rule, register
from .symbols import _terminal, _walk_scope

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_POOLISH_RE = re.compile(r"^_?(buf(fer)?_?)?pool$", re.IGNORECASE)
_LIMITERISH_RE = re.compile(r"limit|bucket|shaper", re.IGNORECASE)


def _recv_terminal(call: ast.Call) -> str | None:
    """Terminal name of a method call's receiver: ``limiter`` for both
    ``limiter.acquire(...)`` and ``self.limiter.acquire(...)``."""
    if isinstance(call.func, ast.Attribute):
        return _terminal(call.func.value)
    return None


def _is_pool_acquire(call: ast.Call) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
            and bool(_POOLISH_RE.match(_recv_terminal(call) or "")))


def _is_pool_release(call: ast.Call, var: str) -> bool:
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "release"
            and bool(_POOLISH_RE.match(_recv_terminal(call) or ""))
            and len(call.args) >= 1
            and isinstance(call.args[0], ast.Name)
            and call.args[0].id == var)


def _stmt_lists(fn) -> Iterator[list[ast.stmt]]:
    """Every statement list in this function scope (bodies, else arms,
    handlers, finallys), without descending into nested functions."""
    stack: list[list[ast.stmt]] = [fn.body]
    while stack:
        body = stack.pop()
        yield body
        for stmt in body:
            if isinstance(stmt, _FUNC_NODES):
                continue
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, fld, None)
                if sub and isinstance(sub, list) \
                        and isinstance(sub[0], ast.stmt):
                    stack.append(sub)
            for h in getattr(stmt, "handlers", []) or []:
                stack.append(h.body)


def _refs_var(node: ast.AST, var: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == var
               for n in ast.walk(node))


def _protected_sites(fn, match) -> bool:
    """True when a node satisfying ``match`` lives inside a ``finally``
    body or an ``except`` handler of some try in this scope — the
    shapes that run on the exception path too."""
    for node in _walk_scope(fn.body):
        if not isinstance(node, ast.Try):
            continue
        covered = list(node.finalbody)
        for h in node.handlers:
            covered.extend(h.body)
        for stmt in covered:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and match(sub):
                    return True
    return False


def _suspends_between(fn, lo: int, hi: int) -> bool:
    """Any await / raise strictly inside the (lo, hi) line window — a
    point where the function can unwind with the resource in hand."""
    for node in _walk_scope(fn.body):
        if isinstance(node, (ast.Await, ast.Raise)) \
                and lo < getattr(node, "lineno", lo) < hi:
            return True
    return False


# ---------------------------------------------------------------------------
# DF007 — pooled-buffer lifecycle
# ---------------------------------------------------------------------------

@register
class PooledBufferLifecycle(Rule):
    """DF007: a ``bufpool`` buffer must reach ``release`` on every path,
    never be retained on ``self``/closures, never be touched after
    release.

    Incident (PR 5, made static): the piece-buffer pool recycles the
    4–16 MiB download buffers; its module contract says a released
    buffer may be handed to ANOTHER download at any moment. The contract
    has three failure modes this rule pins:

    * **leak** — an exception path (and in this codebase every ``await``
      is one) unwinds with the buffer still checked out: the pool
      re-allocates, and at fan-out that is the page-fault storm the pool
      exists to kill. ``piece_downloader._read_body`` releases in an
      ``except BaseException`` arm; ``piece_engine`` releases in a
      ``finally`` — those are the two blessed shapes.
    * **retention** — parking the buffer on ``self`` or in a closure
      outlives the release decision and is how a "freed" buffer grows a
      second owner (the never-retain rule PR 5 wrote in prose).
    * **use-after-release** — touching the buffer after ``release``
      reads ANOTHER download's bytes; the pool's export-probe catches
      live memoryviews but a plain reference sails through.

    A buffer that is ``return``ed or ``yield``ed transfers ownership to
    the caller (the ``download_piece`` contract) and is exempt.
    """

    code = "DF007"
    name = "pooled-buffer-lifecycle"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: ModuleCtx, fn) -> Iterator[Finding]:
        acquired: list[tuple[str, ast.Assign]] = []
        for node in _walk_scope(fn.body):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _is_pool_acquire(node.value)):
                acquired.append((node.targets[0].id, node))
        for var, stmt in acquired:
            yield from self._check_var(ctx, fn, var, stmt)

    def _check_var(self, ctx: ModuleCtx, fn, var: str,
                   acq: ast.Assign) -> Iterator[Finding]:
        releases = [n for n in _walk_scope(fn.body)
                    if isinstance(n, ast.Call)
                    and _is_pool_release(n, var)]
        transferred = any(
            isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom))
            and n.value is not None and _refs_var(n.value, var)
            for n in _walk_scope(fn.body))

        # retention: the buffer must never outlive the function's own
        # bookkeeping — not on self, not in a collection, not captured
        for node in _walk_scope(fn.body):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            for t in node.targets)
                    and _refs_var(node.value, var)
                    and node.lineno > acq.lineno):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"pooled buffer {var!r} retained on self — the pool "
                    f"may hand its memory to another download after "
                    f"release; never retain pooled buffers (bufpool "
                    f"contract)")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("append", "add")
                    and any(isinstance(a, ast.Name) and a.id == var
                            for a in node.args)):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"pooled buffer {var!r} stored into a collection — "
                    f"a parked reference outlives the release decision; "
                    f"never retain pooled buffers (bufpool contract)")
        for node in ast.walk(fn):
            if isinstance(node, _FUNC_NODES) and node is not fn \
                    and _refs_var(node, var):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"pooled buffer {var!r} captured by a nested "
                    f"function — the closure can touch recycled memory "
                    f"after release; pass bytes, not the pooled buffer")
                break

        if not releases:
            if not transferred:
                yield Finding(
                    self.code, ctx.rel, acq.lineno, acq.col_offset,
                    f"pooled buffer {var!r} never reaches "
                    f"POOL.release() and is not returned to a caller — "
                    f"every leaked buffer re-allocates 4-16 MiB at "
                    f"fan-out (the churn the pool exists to kill)")
            return

        protected = _protected_sites(
            fn, lambda c: _is_pool_release(c, var))
        last_rel = max(r.lineno for r in releases)
        if not protected and _suspends_between(fn, acq.lineno, last_rel):
            yield Finding(
                self.code, ctx.rel, acq.lineno, acq.col_offset,
                f"pooled buffer {var!r} can leak on the exception path "
                f"— an await/raise sits between acquire and release but "
                f"no release runs in a finally/except; use "
                f"try/finally (piece_engine) or except+release+raise "
                f"(_read_body)")

        # use-after-release: a later statement in the same block that
        # touches the buffer reads another download's bytes. Releases
        # inside except handlers don't poison the fall-through path —
        # the handler's own raise/return already left the block
        # (_read_body's except BaseException: release; raise shape).
        for body in _stmt_lists(fn):
            rel_idx = None
            for i, stmt in enumerate(body):
                if isinstance(stmt, ast.Assign) \
                        and _refs_var(stmt.targets[0], var):
                    rel_idx = None      # rebound: tracking restarts
                    continue
                has_rel = any(isinstance(n, ast.Call)
                              and _is_pool_release(n, var)
                              for n in self._fallthrough_nodes(stmt))
                if rel_idx is not None and _refs_var(stmt, var):
                    yield Finding(
                        self.code, ctx.rel, stmt.lineno, stmt.col_offset,
                        f"pooled buffer {var!r} used after "
                        f"POOL.release() (released at line "
                        f"{body[rel_idx].lineno}) — its memory may "
                        f"already belong to another download")
                    break
                if has_rel:
                    rel_idx = i

    @staticmethod
    def _fallthrough_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
        """Nodes of ``stmt`` that run on the path that *continues past*
        it — skips except-handler bodies (they unwind or re-raise) and
        nested functions."""
        stack: list[ast.AST] = [stmt]
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, _FUNC_NODES):
                continue
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.ExceptHandler, *_FUNC_NODES)):
                    continue
                stack.append(c)


# ---------------------------------------------------------------------------
# DF008 — acquire/refund pairing for leases and limiter tokens
# ---------------------------------------------------------------------------

@register
class AcquireRefundPairing(Rule):
    """DF008: every optimistic acquire must be dominated by its paired
    release on all exits, exception paths included.

    Incident family (PR 5's 404-refund, PR 9's eviction-refund): a
    limiter token represents bytes *about to move*; when the move fails
    (404 after an optimistic acquire, a write that raises, an evicted
    span) the tokens must come back via ``refund`` or the bucket's
    capacity leaks one failure at a time until the pipe is "full" of
    ghost traffic. Same family: upload/QoS slots acquired as objects
    (``slot = await gate.acquire()``) that must ``release()`` on every
    path or the gate wedges shut.

    Two arms:

    * **token pairing** — in a function that refunds a limiter anywhere
      (proof the acquires here are optimistic), every ``await
      X.acquire(n)`` must sit inside — or be directly followed by — a
      ``try`` whose handler/finally refunds ``X``. The blessed shape is
      upload_server's: acquire, then try/write/except refund+raise.
    * **lease objects** — a var bound from ``await X.acquire(...)``
      whose ``release()`` this function owns must have a release on the
      exception path (finally/except) when awaits separate acquire from
      release; a lease with NO release that isn't handed off (returned,
      stored, passed to a call) is flagged outright.
    """

    code = "DF008"
    name = "acquire-refund-pairing"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._tokens(ctx, fn)
            yield from self._leases(ctx, fn)

    # -- arm 1: limiter tokens -------------------------------------------

    def _tokens(self, ctx: ModuleCtx, fn) -> Iterator[Finding]:
        refunded: set[str] = set()
        for node in _walk_scope(fn.body):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "refund"):
                recv = _recv_terminal(node)
                if recv:
                    refunded.add(recv)
        if not refunded:
            return      # no refunds here: these acquires pay for bytes
                        # already moved — nothing optimistic to pair
        yield from self._visit_block(ctx, fn.body, frozenset(), refunded)

    @staticmethod
    def _try_refunds(stmt: ast.stmt) -> frozenset[str]:
        """Receivers a try statement refunds on unwind (handler or
        finally) — the coverage an acquire inside/before it enjoys."""
        if not isinstance(stmt, ast.Try):
            return frozenset()
        covered = list(stmt.finalbody)
        for h in stmt.handlers:
            covered.extend(h.body)
        out = set()
        for s in covered:
            for n in ast.walk(s):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "refund"):
                    recv = _recv_terminal(n)
                    if recv:
                        out.add(recv)
        return frozenset(out)

    def _visit_block(self, ctx: ModuleCtx, body: list[ast.stmt],
                     covered: frozenset[str],
                     refunded: set[str]) -> Iterator[Finding]:
        """Walk one statement list. An acquire is refund-covered when an
        enclosing try refunds its receiver on unwind (sound — the
        handler/finally runs however the region exits), or when a try
        later in the same block does AND nothing that can unwind (an
        await or raise outside a try) stands between them — the
        acquire-then-guarded-consume shape upload_server uses.
        ``covered`` carries only the sound enclosing-try coverage into
        nested blocks: a later try in an outer list does NOT protect an
        acquire inside a loop body, because an exception mid-iteration
        never reaches it."""
        for i, stmt in enumerate(body):
            later = set()
            for nxt in body[i + 1:]:
                if isinstance(nxt, ast.Try):
                    # take the try's refunds, then stop if it can
                    # unwind: an exception its handlers don't catch
                    # skips every try after it, so coverage further
                    # down the list is unreachable from here
                    later |= self._try_refunds(nxt)
                    if any(isinstance(n, (ast.Await, ast.Raise))
                           for n in _walk_scope([nxt])):
                        break
                elif any(isinstance(n, (ast.Await, ast.Raise))
                         for n in _walk_scope([nxt])):
                    break       # this statement can unwind first
            eff = covered | later | self._try_refunds(stmt)
            for node in self._expr_nodes(stmt):
                if not (isinstance(node, ast.Await)
                        and isinstance(node.value, ast.Call)):
                    continue
                call = node.value
                if not (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "acquire"):
                    continue
                recv = _recv_terminal(call)
                if recv in refunded and recv not in eff:
                    yield Finding(
                        self.code, ctx.rel, node.lineno, node.col_offset,
                        f"optimistic await {recv}.acquire(…) without a "
                        f"refund on the failure path — this function "
                        f"refunds {recv} elsewhere, so tokens here "
                        f"stand for bytes that may never move; wrap the "
                        f"consume in try/except {recv}.refund(…) "
                        f"(PR 5 404-refund contract)")
            down = covered | self._try_refunds(stmt)
            if isinstance(stmt, _FUNC_NODES):
                continue
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, fld, None)
                if sub and isinstance(sub, list) \
                        and isinstance(sub[0], ast.stmt):
                    yield from self._visit_block(ctx, sub, down, refunded)
            for h in getattr(stmt, "handlers", []) or []:
                yield from self._visit_block(ctx, h.body, down, refunded)

    @staticmethod
    def _expr_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
        """Expression-level nodes of one statement: stop at nested
        statements (they get their own block visit) and functions."""
        stack: list[ast.AST] = [stmt]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.stmt, ast.ExceptHandler)) \
                        or isinstance(c, _FUNC_NODES):
                    continue
                stack.append(c)

    # -- arm 2: lease objects --------------------------------------------

    def _leases(self, ctx: ModuleCtx, fn) -> Iterator[Finding]:
        leases: list[tuple[str, ast.Assign]] = []
        for node in _walk_scope(fn.body):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Await)
                    and isinstance(node.value.value, ast.Call)
                    and isinstance(node.value.value.func, ast.Attribute)
                    and node.value.value.func.attr == "acquire"):
                leases.append((node.targets[0].id, node))
        for var, acq in leases:
            releases = [
                n for n in _walk_scope(fn.body)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "release"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var]
            if not releases:
                handed_off = any(
                    (isinstance(n, (ast.Return, ast.Yield))
                     and n.value is not None and _refs_var(n.value, var))
                    or (isinstance(n, ast.Call)
                        and any(isinstance(a, ast.Name) and a.id == var
                                for a in n.args))
                    or (isinstance(n, ast.Assign)
                        and any(isinstance(t, ast.Attribute)
                                for t in n.targets)
                        and _refs_var(n.value, var))
                    for n in _walk_scope(fn.body))
                if not handed_off:
                    yield Finding(
                        self.code, ctx.rel, acq.lineno, acq.col_offset,
                        f"lease {var!r} acquired but never released or "
                        f"handed off — an unreleased slot wedges the "
                        f"gate shut for every later acquirer")
                continue
            protected = _protected_sites(
                fn, lambda c: (isinstance(c.func, ast.Attribute)
                               and c.func.attr == "release"
                               and isinstance(c.func.value, ast.Name)
                               and c.func.value.id == var))
            last_rel = max(r.lineno for r in releases)
            if not protected \
                    and _suspends_between(fn, acq.lineno, last_rel):
                yield Finding(
                    self.code, ctx.rel, acq.lineno, acq.col_offset,
                    f"lease {var!r} can leak on the exception path — an "
                    f"await/raise sits between acquire and release but "
                    f"no release runs in a finally/except; an abandoned "
                    f"slot starves the gate (upload-slot discipline)")


# ---------------------------------------------------------------------------
# DF008 — tmp-file fd release on persist paths (statestore idiom)
# ---------------------------------------------------------------------------

def _is_raw_open(call: ast.Call) -> bool:
    """``open(...)`` or ``os.fdopen(...)`` — a file object whose close
    this function owns (a ``with`` block never binds through Assign, so
    it is exempt by construction)."""
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        return True
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "fdopen"
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "os")


def _calls_os_replace(fn) -> bool:
    for node in _walk_scope(fn.body):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "replace"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "os"):
            return True
    return False


@register
class TmpFdRelease(Rule):
    """DF008 family: a persist path using the tmp+rename idiom must
    release its tmp-file fd on the exception path.

    Incident class (PR 17, made static): ``statestore.save`` runs on the
    GC ticker and swallows every failure by design — the snapshot that
    cannot land must never block a ruling, so the NEXT tick retries. On
    an ENOSPC'd or wedged disk that means the torn ``f.write`` raises
    every few seconds forever; with the fd closed only on the
    straight-line path, each retry leaks one descriptor and the process
    walks into EMFILE — at which point the scheduler cannot accept
    connections either, and the "best-effort" snapshot has taken the
    control plane down with it.

    The rule fires on any function that performs the idiom (calls
    ``os.replace``) and binds a raw ``open()``/``os.fdopen()`` to a
    name: the ``close()`` must run in a ``finally`` or ``except`` arm
    (the ``statestore._write`` / ``TaskMetadata.save``-with-``with``
    shapes). A straight-line-only close sits after writes that raise on
    a full disk; no close at all leaks even on success.
    """

    code = "DF008"
    name = "tmp-fd-release"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _calls_os_replace(fn):
                continue
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: ModuleCtx, fn) -> Iterator[Finding]:
        for node in _walk_scope(fn.body):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _is_raw_open(node.value)):
                continue
            var = node.targets[0].id
            closes = [
                n for n in _walk_scope(fn.body)
                if isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "close"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var]
            if not closes:
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"tmp-file fd {var!r} on a tmp+rename persist path "
                    f"is never closed — every retry of a failing persist "
                    f"leaks one fd until EMFILE; close it in a finally "
                    f"(statestore._write shape) or use `with`")
                continue
            protected = _protected_sites(
                fn, lambda c: (isinstance(c.func, ast.Attribute)
                               and c.func.attr == "close"
                               and isinstance(c.func.value, ast.Name)
                               and c.func.value.id == var))
            if not protected:
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"tmp-file fd {var!r} closes only on the straight-"
                    f"line path — a torn write (ENOSPC, the "
                    f"sched.snapshot.io fault) raises before close and "
                    f"the retry loop leaks one fd per tick; move the "
                    f"close into a finally (statestore._write shape) or "
                    f"use `with`")
