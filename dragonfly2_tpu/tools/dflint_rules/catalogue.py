"""DF006: observable-vocabulary catalogue lints, consolidated.

These started life as three ad-hoc runtime lints buried in
tests/test_observability.py (metric catalogue) and tests/test_faults.py
(faultgate sites, rung names): walk the live registry after importing
every service, then diff against the docs. Moving them into dflint makes
them static (no imports, so a module nobody imports is still covered),
gives them the one shared suppression grammar, and leaves ONE registry,
ONE walker, ONE output format for every project invariant.

Incident (PR 3 audit): docs/OBSERVABILITY.md trailed the code by a third
of the metric namespace — a metric that exists only in code is invisible
to operators, and an undocumented flight-event kind or ladder rung is a
/debug/flight surface nobody can read.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from . import Finding, ModuleCtx, Rule, register
from .concurrency import _terminal

_METRIC_METHODS = frozenset({"counter", "gauge", "histogram"})
_FIRE_RE = re.compile(
    r"faultgate\.(?:fire|fire_sync|corrupt)\(\s*[\"']([a-z.]+)[\"']")
_TICK_RE = re.compile(r"`([a-z0-9_.-]+)`")   # hyphens: exclusion reasons
_METRIC_NAME_RE = re.compile(r"df_[a-z0-9_]+")


def _read_doc(ctx: ModuleCtx, name: str) -> str | None:
    key = f"doc:{name}"
    if key not in ctx.project:
        path = os.path.join(ctx.repo_root, "docs", name)
        try:
            with open(path, encoding="utf-8") as f:
                ctx.project[key] = f.read()
        except OSError:
            ctx.project[key] = None
    return ctx.project[key]


def _doc_metric_names(ctx: ModuleCtx) -> set[str] | None:
    if "doc_metrics" not in ctx.project:
        doc = _read_doc(ctx, "OBSERVABILITY.md")
        ctx.project["doc_metrics"] = (
            None if doc is None else set(_METRIC_NAME_RE.findall(doc)))
    return ctx.project["doc_metrics"]


def _ticked(ctx: ModuleCtx, name: str) -> set[str]:
    key = f"ticked:{name}"
    if key not in ctx.project:
        doc = _read_doc(ctx, name)
        ctx.project[key] = set() if doc is None else \
            set(_TICK_RE.findall(doc))
    return ctx.project[key]


@register
class MetricCatalogue(Rule):
    """DF006 (metrics): every registered metric must be ``df_``-prefixed,
    carry help text, and appear in docs/OBSERVABILITY.md.

    Replaces tests/test_observability.py's runtime registry walk (PR 1
    metric-namespace lint + PR 3 catalogue lint). Static analysis covers
    modules the old import list forgot to enumerate.
    """

    code = "DF006"
    name = "metric-catalogue"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            mname = node.args[0].value
            if not mname.startswith("df_"):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"metric {mname!r} is outside the df_ namespace — "
                    f"every metric this fabric exports is df_-prefixed")
                continue
            help_arg = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                help_arg = node.args[1].value
            elif len(node.args) < 2:
                for kw in node.keywords:
                    if kw.arg == "help_" and isinstance(kw.value,
                                                        ast.Constant):
                        help_arg = kw.value.value
            if isinstance(help_arg, str) and not help_arg.strip() \
                    or (len(node.args) < 2
                        and not any(kw.arg == "help_"
                                    for kw in node.keywords)):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"metric {mname!r} registered without help text — "
                    f"/metrics must stay self-describing as it grows")
            documented = _doc_metric_names(ctx)
            if documented is None:
                if not ctx.project.get("warned_no_obs_doc"):
                    ctx.project["warned_no_obs_doc"] = True
                    yield Finding(
                        self.code, ctx.rel, node.lineno, node.col_offset,
                        "docs/OBSERVABILITY.md not found — the metric "
                        "catalogue has nothing to lint against")
            elif mname not in documented:
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"metric {mname!r} is not documented in "
                    f"docs/OBSERVABILITY.md — a metric that exists only "
                    f"in code is invisible to operators")


@register
class FlightVocabulary(Rule):
    """DF006 (flight recorder): every event kind and ladder rung the
    journal can emit must be backticked in the docs (kinds in
    OBSERVABILITY.md; rungs there or in RESILIENCE.md, where the ladder
    lives). An undocumented stage in a /debug/flight dump is a surface
    operators cannot read. Replaces the runtime vocabulary lint."""

    code = "DF006"
    name = "flight-vocabulary"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith(
                "daemon/flight_recorder.py"):
            return
        obs = _ticked(ctx, "OBSERVABILITY.md")
        any_doc = obs | _ticked(ctx, "RESILIENCE.md")
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            if not (isinstance(value, ast.Constant)
                    and isinstance(value.value, str) and value.value):
                continue
            for tgt in targets:
                if not (isinstance(tgt, ast.Name) and tgt.id.isupper()):
                    continue
                if tgt.id.startswith("RUNG_"):
                    if value.value not in any_doc:
                        yield Finding(
                            self.code, ctx.rel, node.lineno,
                            node.col_offset,
                            f"ladder rung {value.value!r} ({tgt.id}) is "
                            f"emitted in flight journals but undocumented "
                            f"in docs/OBSERVABILITY.md or RESILIENCE.md")
                elif value.value not in obs:
                    yield Finding(
                        self.code, ctx.rel, node.lineno, node.col_offset,
                        f"flight event kind {value.value!r} ({tgt.id}) is "
                        f"emitted in flight journals but undocumented in "
                        f"docs/OBSERVABILITY.md")


@register
class DecisionVocabulary(Rule):
    """DF006 (decision ledger): the scheduling filter's exclusion-reason
    vocabulary must stay closed and documented — the ``EXCLUSION_REASONS``
    registry in ``scheduler/scheduling.py``, the literal reasons passed to
    ``Scheduling._trace`` (which become ``df_sched_filter_excluded_total``
    labels and decision-row ``excluded`` entries), and the backticked
    vocabulary in docs/OBSERVABILITY.md must agree. Same contract as the
    flight-kind/rung and faultgate-site lints: an unregistered reason is
    an invisible metric label, a registered-but-never-fired one is dead
    vocabulary, and an undocumented one is a ledger surface operators
    cannot read.

    Incident (PR 8): filter exclusions survived only as DEBUG log lines —
    a pod herding onto ``no-slots``/``bad-node`` was invisible without
    redeploying at DEBUG, and nothing pinned the reason strings the
    decision ledger now persists.
    """

    code = "DF006"
    name = "decision-vocabulary"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith(
                "scheduler/scheduling.py"):
            return
        declared: dict[str, int] = {}
        declared_line = 1
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "EXCLUSION_REASONS"
                            for t in node.targets)):
                continue
            declared_line = node.lineno
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    declared[const.value] = const.lineno
        fired: dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_trace"
                    and len(node.args) >= 3
                    and isinstance(node.args[2], ast.Constant)
                    and isinstance(node.args[2].value, str)):
                continue
            fired.setdefault(node.args[2].value, node.lineno)
        if not declared and not fired:
            return
        obs = _ticked(ctx, "OBSERVABILITY.md")
        for reason, line in sorted(declared.items()):
            if reason not in fired:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"exclusion reason {reason!r} is registered in "
                    f"EXCLUSION_REASONS but no _trace call fires it — "
                    f"dead vocabulary")
            if reason not in obs:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"exclusion reason {reason!r} is not documented in "
                    f"docs/OBSERVABILITY.md — decision-row excluded "
                    f"entries and the df_sched_filter_excluded_total "
                    f"label are unreadable to operators")
        for reason, line in sorted(fired.items()):
            if reason not in declared:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"_trace fires exclusion reason {reason!r} but it is "
                    f"not in the EXCLUSION_REASONS registry "
                    f"(line {declared_line})")


_PHASE_FIRE_RE = re.compile(
    r"phasetimer\.(?:phase|record)\(\s*[\"']([a-z-]+)[\"']")
_RULING_FIRE_RE = re.compile(
    r"phasetimer\.ruling\(\s*[\"']([a-z-]+)[\"']")


@register
class PhaseVocabulary(Rule):
    """DF006 (ruling profiler): the control-plane phase vocabulary must
    stay closed and documented — the ``PHASES``/``RULING_KINDS``
    registries in ``common/phasetimer.py``, the literals at every
    ``phasetimer.phase(…)``/``record(…)``/``ruling(…)`` call site across
    the package (which become ``df_sched_ruling_seconds``/
    ``df_ctrl_ruling_seconds`` labels and /debug/ctrl rows), and the
    backticked vocabulary in docs/OBSERVABILITY.md must agree. An
    unregistered literal raises ValueError the first armed ruling (the
    registry validates), a registered-but-never-fired phase is dead
    vocabulary, and an undocumented one is a /debug/ctrl surface
    operators cannot read. Ruling kinds are swept one-sided (literal ->
    registered + documented): the main ``_decide`` path passes its kind
    as a variable, so absence of a kind literal proves nothing.
    """

    code = "DF006"
    name = "phase-vocabulary"

    def _declared(self, ctx: ModuleCtx,
                  registry: str) -> tuple[dict[str, int], int]:
        out: dict[str, int] = {}
        reg_line = 1
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == registry
                            for t in node.targets)):
                continue
            reg_line = node.lineno
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    out[const.value] = const.lineno
        return out, reg_line

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith(
                "common/phasetimer.py"):
            return
        phases, phases_line = self._declared(ctx, "PHASES")
        kinds, kinds_line = self._declared(ctx, "RULING_KINDS")
        if not phases and not kinds:
            return
        # package-wide call-site sweep, rooted at the package holding
        # this file (…/common/phasetimer.py -> …/); dflint_rules holds
        # these regexes themselves, not call sites
        pkg_root = os.path.dirname(os.path.dirname(ctx.path))
        fired_phases: set[str] = set()
        fired_kinds: set[str] = set()
        for dirpath, dirs, files in os.walk(pkg_root):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", "dflint_rules")]
            for name in files:
                if not name.endswith(".py") or name == "phasetimer.py":
                    continue
                try:
                    with open(os.path.join(dirpath, name),
                              encoding="utf-8") as f:
                        src = f.read()
                except OSError:
                    continue
                fired_phases.update(_PHASE_FIRE_RE.findall(src))
                fired_kinds.update(_RULING_FIRE_RE.findall(src))
        obs = _ticked(ctx, "OBSERVABILITY.md")
        for ph, line in sorted(phases.items()):
            if ph not in fired_phases:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"phase {ph!r} is registered in PHASES but no "
                    f"phasetimer.phase/record call fires it — dead "
                    f"vocabulary")
            if ph not in obs:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"phase {ph!r} is not documented in "
                    f"docs/OBSERVABILITY.md — the "
                    f"df_sched_ruling_seconds label and /debug/ctrl "
                    f"rows are unreadable to operators")
        for kind, line in sorted(kinds.items()):
            if kind not in obs:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"ruling kind {kind!r} is not documented in "
                    f"docs/OBSERVABILITY.md")
        for ph in sorted(fired_phases - set(phases)):
            yield Finding(
                self.code, ctx.rel, phases_line, 0,
                f"phasetimer.phase({ph!r}) appears in the package but "
                f"{ph!r} is not in the PHASES registry — the first "
                f"armed ruling raises ValueError")
        for kind in sorted(fired_kinds - set(kinds)):
            yield Finding(
                self.code, ctx.rel, kinds_line, 0,
                f"phasetimer.ruling({kind!r}) appears in the package "
                f"but {kind!r} is not in the RULING_KINDS registry — "
                f"the first armed ruling raises ValueError")


_CLASS_USE_RES = (
    # qos_class == / != / = "x"  (comparisons, assignments, kwargs)
    re.compile(r"qos_class\s*(?:==|!=|=)\s*[\"']([a-z_]+)[\"']"),
    # getattr(x, "qos_class", "x") / d.get("qos_class", "x") defaults
    re.compile(r"(?:getattr\([^)]*|\.get\(\s*)"
               r"[\"']qos_class[\"']\s*,\s*[\"']([a-z_]+)[\"']"),
    # cls == / != "x"  (the short-name form the hot paths use)
    re.compile(r"\bcls\s*(?:==|!=)\s*[\"']([a-z_]+)[\"']"),
)


@register
class PriorityClassVocabulary(Rule):
    """DF006 (QoS classes): the multi-tenant service-class vocabulary
    must stay closed and documented — the ``PRIORITY_CLASSES`` registry
    in ``idl/messages.py``, every class literal any surface binds or
    compares to a ``qos_class``/``cls`` (admission gates, shaper splits,
    scheduler rulings, metric labels), and the backticked vocabulary in
    docs/OBSERVABILITY.md / docs/RESILIENCE.md must agree. Same contract
    as the exclusion-reason lint: an unregistered class is an invisible
    metric label and an unenforceable quota row; an undocumented one is
    a ``df_qos_*`` dimension operators cannot read.

    Incident (PR 11): the QoS plane threads one class string through
    eleven surfaces across four services — one typo'd literal at any of
    them would silently route traffic as ``standard`` (resolve_class
    clamps unknowns by design) and the brownout would never engage for
    it.
    """

    code = "DF006"
    name = "priority-class-vocabulary"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith("idl/messages.py"):
            return
        declared: dict[str, int] = {}
        declared_line = 1
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "PRIORITY_CLASSES"
                            for t in node.targets)):
                continue
            declared_line = node.lineno
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    declared[const.value] = const.lineno
        if not declared:
            return
        # package-wide surface sweep, rooted at the package holding this
        # file (…/idl/messages.py -> …/) so fixtures self-contain
        pkg_root = os.path.dirname(os.path.dirname(ctx.path))
        used: dict[str, str] = {}
        for dirpath, dirs, files in os.walk(pkg_root):
            # the analyzer's own rule definitions carry the patterns as
            # examples — sweeping them would lint the linter
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", "dflint_rules")]
            for name in files:
                if not name.endswith(".py") or name == "messages.py":
                    continue
                fpath = os.path.join(dirpath, name)
                try:
                    with open(fpath, encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    continue
                for rx in _CLASS_USE_RES:
                    for m in rx.finditer(text):
                        used.setdefault(m.group(1), fpath)
        docs = _ticked(ctx, "OBSERVABILITY.md") \
            | _ticked(ctx, "RESILIENCE.md")
        for cls, line in sorted(declared.items()):
            if cls not in docs:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"priority class {cls!r} is not backticked in "
                    f"docs/OBSERVABILITY.md or docs/RESILIENCE.md — a "
                    f"service class operators cannot read about cannot "
                    f"be operated")
        for cls in sorted(set(used) - set(declared)):
            yield Finding(
                self.code, ctx.rel, declared_line, 0,
                f"class literal {cls!r} is bound/compared to a "
                f"qos_class surface in "
                f"{os.path.relpath(used[cls], pkg_root)} but is not in "
                f"the PRIORITY_CLASSES registry — resolve_class would "
                f"silently clamp it to 'standard' and the QoS plane "
                f"would never engage for it")


@register
class FaultgateSites(Rule):
    """DF006 (faultgate): the site registry, the ``faultgate.fire(…)``
    call sites across the package, and docs/RESILIENCE.md must agree —
    a registered-but-never-fired site is a chaos surface that tests
    nothing, a fired-but-unregistered name raises at arm time, and an
    undocumented site can't be scripted by operators. Replaces the
    runtime site lint from tests/test_faults.py."""

    code = "DF006"
    name = "faultgate-sites"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith("common/faultgate.py"):
            return
        sites: dict[str, int] = {}
        sites_line = 1
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "SITES"
                            for t in node.targets)):
                continue
            sites_line = node.lineno
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    sites[const.value] = const.lineno
        if not sites:
            return
        # package-wide fire() sweep, rooted at the package holding this
        # file (…/common/faultgate.py -> …/) so fixtures self-contain
        pkg_root = os.path.dirname(os.path.dirname(ctx.path))
        fired: set[str] = set()
        for dirpath, dirs, files in os.walk(pkg_root):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for name in files:
                if not name.endswith(".py") or name == "faultgate.py":
                    continue
                try:
                    with open(os.path.join(dirpath, name),
                              encoding="utf-8") as f:
                        fired.update(_FIRE_RE.findall(f.read()))
                except OSError:
                    continue
        res = _ticked(ctx, "RESILIENCE.md")
        for site, line in sorted(sites.items()):
            if site not in fired:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"faultgate site {site!r} is registered but never "
                    f"fired anywhere in the package — dead chaos surface")
            if site not in res:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"faultgate site {site!r} is not documented in "
                    f"docs/RESILIENCE.md")
        for site in sorted(fired - set(sites)):
            yield Finding(
                self.code, ctx.rel, sites_line, 0,
                f"faultgate.fire({site!r}) appears in the package but "
                f"{site!r} is not in the SITES registry — arming it "
                f"raises ValueError")


_ANOMALY_FIRE_RE = re.compile(r"\._fire\(\s*[\"']([a-z-]+)[\"']")


@register
class AnomalyVocabulary(Rule):
    """DF006 (fleet pulse): the anomaly-kind vocabulary must stay closed
    and documented — the ``ANOMALY_KINDS`` registry in
    ``scheduler/fleetpulse.py``, the kind literal at every
    ``._fire(…)`` call site across the package (each becomes a
    ``df_fleet_anomalies_total`` label, a ``decision_kind=anomaly``
    ledger row, and an incident-bundle id), and the backticked
    vocabulary in docs/OBSERVABILITY.md must agree. A
    registered-but-never-fired kind is dead vocabulary the detector can
    never produce, a fired-but-unregistered kind is an invisible metric
    label dfbench --pr18's injection matrix never covers, and an
    undocumented one is a /debug/fleet surface operators cannot read.
    Unlike the phase sweep, the registry file itself IS swept: the
    detector's fire sites live beside the registry by design.
    """

    code = "DF006"
    name = "anomaly-vocabulary"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        if not ctx.rel.replace(os.sep, "/").endswith(
                "scheduler/fleetpulse.py"):
            return
        declared: dict[str, int] = {}
        declared_line = 1
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "ANOMALY_KINDS"
                            for t in node.targets)):
                continue
            declared_line = node.lineno
            for const in ast.walk(node.value):
                if isinstance(const, ast.Constant) \
                        and isinstance(const.value, str):
                    declared[const.value] = const.lineno
        if not declared:
            return
        # the z-score path fires through the _SIGNALS mapping (signal ->
        # (kind, floor)): the tuple HEADS are fire sites too, read from
        # the same AST so the mapping and the literal sweep agree
        fired: dict[str, str] = {}
        for node in ctx.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "_SIGNALS"
                            for t in node.targets)):
                continue
            for tup in ast.walk(node.value):
                if isinstance(tup, ast.Tuple) and tup.elts \
                        and isinstance(tup.elts[0], ast.Constant) \
                        and isinstance(tup.elts[0].value, str):
                    fired.setdefault(tup.elts[0].value, ctx.path)
        # package-wide fire sweep rooted at the package holding this
        # file (…/scheduler/fleetpulse.py -> …/), INCLUDING fleetpulse.py
        # itself — the detector fires beside its registry
        pkg_root = os.path.dirname(os.path.dirname(ctx.path))
        for dirpath, dirs, files in os.walk(pkg_root):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", "dflint_rules")]
            for name in files:
                if not name.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, name)
                try:
                    with open(fpath, encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    continue
                for m in _ANOMALY_FIRE_RE.finditer(text):
                    fired.setdefault(m.group(1), fpath)
        obs = _ticked(ctx, "OBSERVABILITY.md")
        for kind, line in sorted(declared.items()):
            if kind not in fired:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"anomaly kind {kind!r} is registered in "
                    f"ANOMALY_KINDS but no _fire call emits it — dead "
                    f"vocabulary the detector can never produce")
            if kind not in obs:
                yield Finding(
                    self.code, ctx.rel, line, 0,
                    f"anomaly kind {kind!r} is not documented in "
                    f"docs/OBSERVABILITY.md — a "
                    f"df_fleet_anomalies_total label and /debug/fleet "
                    f"row operators cannot read")
        for kind in sorted(set(fired) - set(declared)):
            yield Finding(
                self.code, ctx.rel, declared_line, 0,
                f"_fire({kind!r}) appears in "
                f"{os.path.relpath(fired[kind], pkg_root)} but {kind!r} "
                f"is not in the ANOMALY_KINDS registry — an invisible "
                f"anomaly label the --pr18 injection matrix never "
                f"covers")
