"""dflint's package index: the two-pass engine's first pass.

PR 7's rules stopped at module boundaries — DF001 "follows module-local
call edges" and goes blind at every ``import``, while the post-mortems of
PRs 9–14 are all *interprocedural* shapes (a blocking helper in
``common/`` called from a coroutine in ``daemon/``, an admission await
taken while holding the ptm lock). This module is the fix's foundation:

* **Index pass** — parse every module under one package root, build
  per-module symbol tables (module-level defs, classes/methods, import
  bindings resolved *within* the package, lock constructors, ``self.x =
  Ctor()`` attribute types), then compute per-function **summaries** to a
  fixpoint over the package-wide call graph:

  - ``blocking`` — calling this (sync) function may execute blocking
    IO/CPU on the caller's thread (the DF001 payload);
  - ``slow``     — awaiting this coroutine may wait on network/timer
    primitives (the DF005 payload);
  - ``parks``    — awaiting this coroutine may park on capacity
    (a future/Condition/semaphore admission wait — the DF009
    priority-inversion payload);
  - ``acquires`` — asyncio locks this function may take, directly or
    transitively (the DF009 lock-ordering graph's edge source).

* **Analysis pass** (the rules) — resolves each call site against the
  index and consults the callee's summary, so a hazard is reported at
  the *call site in the caller's module*. That direction matters twice:
  it is where the fix goes (hop through an executor / move the call out
  of the lock scope), and it makes per-module result caching sound —
  a module's findings depend only on its own text plus the *interfaces*
  of the modules it imports (``ModuleIndex.interface_digest``), never on
  who imports it.

Resolution is deliberately a heuristic subset of Python (no inheritance
walk, no flow typing): module-level defs, class methods via ``self``/
``cls``, imported symbols/modules, module-level singletons (``POOL =
BufferPool()``), and ``self.attr`` receivers whose class is pinned by a
constructor assignment or an annotated ``__init__`` parameter. That set
covers every call edge in this codebase's own incidents; anything it
cannot resolve simply stays un-analyzed, exactly like v1.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterator

__all__ = [
    "FuncKey", "FuncInfo", "Summary", "ModuleIndex", "PackageIndex",
    "package_root_for", "display",
]

# ---------------------------------------------------------------------------
# shared AST helpers (v1 lived in concurrency.py; the index is the one
# place every rule family now imports them from)
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a pure Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> str | None:
    """The last segment of a call target: `x` for x(), `m` for a.b.m()."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _walk_scope(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function scopes.

    A nested sync ``def`` or ``lambda`` inside a coroutine is (in this
    codebase) almost always an executor thunk or a callback — its body
    does not run on the event loop in the coroutine's context, so
    blocking calls there are exactly the *fix* for DF001, not the bug.
    Nested ``async def``s are separate coroutines and are visited in
    their own right by the rules' outer loops.
    """
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue    # a def seeded directly from `body` stays opaque too
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# the blocking-call table (DF001's vocabulary; summaries reuse it)
# ---------------------------------------------------------------------------

_OS_IO = frozenset({
    "stat", "lstat", "listdir", "scandir", "walk", "remove", "unlink",
    "rename", "replace", "makedirs", "mkdir", "rmdir", "removedirs",
    "fsync", "ftruncate", "truncate", "utime", "link", "symlink",
    "chmod", "chown", "statvfs", "system", "popen",
})
_OSPATH_IO = frozenset({
    "getsize", "getmtime", "getctime", "exists", "isfile", "isdir",
    "islink", "samefile", "realpath",
})
_SHUTIL_IO = frozenset({
    "rmtree", "copy", "copy2", "copyfile", "copyfileobj", "copytree",
    "move", "disk_usage", "which",
})
_SOCKET_IO = frozenset({
    "getaddrinfo", "gethostbyname", "gethostbyaddr", "create_connection",
    "getfqdn",
})
_PATHLIB_IO = frozenset({
    "read_bytes", "read_text", "write_bytes", "write_text",
})
_DIGEST_HELPERS = frozenset({"hash_bytes", "hash_file"})
_FILE_METHODS = frozenset({"read", "write", "readline", "readlines",
                           "writelines"})


def _blocking_reason(call: ast.Call) -> str | None:
    d = _dotted(call.func)
    t = _terminal(call.func)
    if d in ("open", "io.open"):
        return "blocking open() — route file IO through an executor"
    if d == "time.sleep":
        return "time.sleep() parks the whole event loop — use asyncio.sleep"
    if d is not None:
        head, _, rest = d.partition(".")
        if head == "subprocess":
            return f"subprocess.{rest or d} blocks the loop — use " \
                   f"asyncio.create_subprocess_*"
        if head == "os" and rest in _OS_IO:
            return f"os.{rest} does synchronous IO on the loop thread"
        if d.startswith("os.path.") and d[len("os.path."):] in _OSPATH_IO:
            return f"{d} stats the filesystem on the loop thread"
        if head == "shutil" and rest in _SHUTIL_IO:
            return f"shutil.{rest} does synchronous IO on the loop thread"
        if head == "socket" and rest in _SOCKET_IO:
            return f"socket.{rest} can block on DNS/connect — use the " \
                   f"loop's async equivalents"
        if head == "hashlib" and call.args:
            return "whole-buffer hashlib digest on the loop thread — " \
                   "hash off-loop (see storage write_span / PR 5)"
    if t in _DIGEST_HELPERS:
        return f"{t}() traverses the whole buffer on the loop thread"
    if t in _PATHLIB_IO:
        return f".{t}() does synchronous file IO on the loop thread"
    return None


def _scan_blocking(fn_body: list[ast.stmt]) -> Iterator[tuple[ast.Call, str]]:
    """Yield (call, reason) for blocking calls lexically in this scope,
    plus reads/writes on file handles and hasher updates bound here."""
    handles: set[str] = set()
    hashers: set[str] = set()
    for node in _walk_scope(fn_body):
        if isinstance(node, ast.With):
            for item in node.items:
                if (isinstance(item.context_expr, ast.Call)
                        and _dotted(item.context_expr.func)
                        in ("open", "io.open")
                        and isinstance(item.optional_vars, ast.Name)):
                    handles.add(item.optional_vars.id)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if d in ("open", "io.open"):
                    handles.add(tgt.id)
                elif d is not None and d.startswith("hashlib."):
                    hashers.add(tgt.id)
    for node in _walk_scope(fn_body):
        if not isinstance(node, ast.Call):
            continue
        reason = _blocking_reason(node)
        if reason is not None:
            yield node, reason
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
            if f.value.id in handles and f.attr in _FILE_METHODS:
                yield node, (f"{f.value.id}.{f.attr}() on a blocking file "
                             f"handle — route file IO through an executor")
            elif f.value.id in hashers and f.attr == "update":
                yield node, ("whole-buffer hasher.update on the loop "
                             "thread — hash off-loop (PR 5 zero-stall rule)")


# ---------------------------------------------------------------------------
# slow/park await vocabulary (DF005 / DF009 payloads)
# ---------------------------------------------------------------------------

_LOCKISH_RE = re.compile(r"lock|cond|sem|mutex", re.IGNORECASE)
_CONDISH_RE = re.compile(r"cond", re.IGNORECASE)
_FUTURISH_RE = re.compile(r"fut|waiter|promise", re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"queue|\bq\b|_q$", re.IGNORECASE)
_SLOW_AWAITS = frozenset({
    "sleep", "gather", "wait", "wait_for", "open_connection",
    "getaddrinfo", "connect", "request", "get", "post", "put", "patch",
    "delete", "fetch", "recv", "read", "readexactly", "readline",
    "readuntil", "drain", "send", "send_json", "json", "text",
})


def _park_reason(awaited: ast.expr,
                 lock_kind) -> str | None:
    """Why this awaited expression may park on *capacity* (an admission
    wait) rather than on the network: a future, a Condition wait, a
    semaphore/queue acquire. ``lock_kind(name)`` resolves ctor evidence.

    Parking is the DF009 payload — the PR 11 incident was precisely an
    admission future awaited while the ptm lock was held."""
    if isinstance(awaited, ast.Name) and _FUTURISH_RE.search(awaited.id):
        return f"awaits future {awaited.id!r} (capacity/admission wait)"
    if not isinstance(awaited, ast.Call):
        return None
    fn = awaited.func
    t = _terminal(fn)
    if t == "wait_for" and awaited.args:
        inner = awaited.args[0]
        if isinstance(inner, ast.Name) and _FUTURISH_RE.search(inner.id):
            return f"waits on future {inner.id!r} with a deadline " \
                   f"(queue-admission wait)"
        if isinstance(inner, ast.Call):
            it = _terminal(inner.func)
            recv = _terminal(inner.func.value) or "" \
                if isinstance(inner.func, ast.Attribute) else ""
            if it == "wait" and (lock_kind(recv) == "cond"
                                 or _CONDISH_RE.search(recv)):
                return f"waits on condition {recv!r} with a deadline"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    recv = _terminal(fn.value) or ""
    if t == "wait" and (lock_kind(recv) == "cond"
                        or _CONDISH_RE.search(recv)):
        return f"parks on condition {recv!r}"
    if t == "acquire" and (lock_kind(recv) in ("lock", "cond")
                           or _LOCKISH_RE.search(recv)):
        return f"parks acquiring {recv!r}"
    if t in ("get", "put", "join") and _QUEUEISH_RE.search(recv):
        return f"parks on queue {recv!r}"
    return None


# ---------------------------------------------------------------------------
# per-function summary
# ---------------------------------------------------------------------------

FuncKey = tuple[str, str, str]      # (module dotted, class or '', name)


def display(key: FuncKey, top: str = "") -> str:
    """Human form of a FuncKey: daemon.qos.QosGovernor.admit."""
    mod, cls, name = key
    if top and mod.startswith(top + "."):
        mod = mod[len(top) + 1:]
    return ".".join(p for p in (mod, cls, name) if p)


@dataclass
class Summary:
    """What calling/awaiting this function can do to the caller — the
    package-wide interface the analysis pass consults at call sites.
    Each field carries (reason, via) where ``via`` names the function the
    fact was inherited from ('' when direct)."""
    blocking: tuple[str, str] | None = None
    slow: tuple[str, str] | None = None
    parks: tuple[str, str] | None = None
    acquires: dict[str, str] = field(default_factory=dict)   # lock id -> via

    def digest_parts(self) -> tuple:
        return (self.blocking and self.blocking[0],
                self.slow and self.slow[0],
                self.parks and self.parks[0],
                tuple(sorted(self.acquires)))


@dataclass
class FuncInfo:
    key: FuncKey
    node: ast.FunctionDef | ast.AsyncFunctionDef
    is_async: bool
    # resolved call edges: (kind 'call'|'await', callee FuncKey, lineno)
    edges: list[tuple[str, FuncKey, int]] = field(default_factory=list)


# ---------------------------------------------------------------------------
# per-module index
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Condition": "cond", "Event": "event", "Lock": "lock",
               "Semaphore": "lock", "BoundedSemaphore": "lock"}

#: THE suppression grammar — the finding pass (scan_suppressions, the
#: DF000 audit) and the index pass (summary-retiring suppressions) must
#: parse the same language or a comment one accepts silently fails in
#: the other; both import this one pattern.
SUPPRESS_RE = re.compile(
    r"#\s*dflint:\s*disable=(?P<codes>DF\d{3}(?:\s*,\s*DF\d{3})*)"
    r"\s*(?:—|–|--+|-)\s*(?P<reason>\S.*?)\s*$")


def _ann_names(expr: ast.expr | None) -> list[str]:
    """Class names mentioned in an annotation: QosGovernor for
    ``QosGovernor | None``, ``Optional[QosGovernor]``, plain names."""
    if expr is None:
        return []
    out = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id[:1].isupper() \
                and node.id not in ("Optional", "Union", "Any", "None"):
            out.append(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # string annotation "QosGovernor"
            name = node.value.strip().split("|")[0].strip()
            if name[:1].isupper():
                out.append(name)
    return out


class ModuleIndex:
    """Symbol tables for one module: defs, classes, lock ctors, import
    bindings (resolved within the package by PackageIndex), and the
    ``self.attr``-type pins that let ``self.qos.admit()`` resolve."""

    def __init__(self, path: str, modname: str, src: str,
                 tree: ast.Module, is_pkg: bool, top: str):
        self.path = path
        self.modname = modname          # dragonfly2_tpu.daemon.announcer
        self.is_pkg = is_pkg            # True for __init__.py
        self.top = top                  # top package name
        self.src = src
        self.tree = tree
        self.content_hash = hashlib.sha256(src.encode()).hexdigest()
        # lines covered by a well-formed disable comment, per code. A
        # reasoned suppression at the *definition* retires
        # the hazard from the function's summary too — otherwise one
        # "hashes ≤KB strings" judgement call would resurface as a
        # finding at every cross-module call site. Comments come from
        # tokenize, same as the finding pass — a raw line regex would
        # also match the grammar quoted inside docstrings/strings and
        # silently retire real hazards with no recorded reason
        self.suppressed: set[tuple[str, int]] = set()
        # (code, hazard line) pairs a summary actually skipped — the
        # unused-suppression audit (DF000) treats the comment covering
        # such a line as used even when no module-local finding matched
        self.summary_used: set[tuple[str, int]] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(src).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []
        for i, text in comments:
            m = SUPPRESS_RE.search(text)
            if m:
                for code in m.group("codes").split(","):
                    self.suppressed.add((code.strip(), i))
                    self.suppressed.add((code.strip(), i + 1))
        # (class or '', name) -> def node; both sync and async
        self.defs: dict[tuple[str, str], ast.AST] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        # local name -> ("mod", dotted) | ("sym", dotted, symbol)
        self.imports: dict[str, tuple] = {}
        self.dotted_mods: set[str] = set()      # plain `import a.b.c`
        # (class or '', lock attr/name) -> 'lock'|'cond'|'event'
        self.lock_ctors: dict[tuple[str, str], str] = {}
        # (class or '', attr) -> local type name (resolved lazily)
        self.attr_types: dict[tuple[str, str], str] = {}
        # module-level singleton: name -> local class/ctor name
        self.instances: dict[str, str] = {}
        self._collect()

    @property
    def disp(self) -> str:
        """Display module path without the top package: daemon.qos."""
        if self.modname.startswith(self.top + "."):
            return self.modname[len(self.top) + 1:]
        return self.modname

    def _collect(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[("", node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self.defs[(node.name, sub.name)] = sub
                self._collect_attrs(node)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                ctor = _terminal(node.value.func)
                for t in node.targets:
                    if isinstance(t, ast.Name) and ctor:
                        if ctor in _LOCK_CTORS:
                            self.lock_ctors[("", t.id)] = _LOCK_CTORS[ctor]
                        else:
                            self.instances[t.id] = ctor
        # module-wide lock-ctor fallback by terminal name, preserving the
        # v1 behavior for assignments anywhere (incl. inside methods)
        for node in ast.walk(self.tree):
            value, targets = None, []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            kind = _LOCK_CTORS.get(_terminal(value.func) or "")
            if kind is None:
                continue
            for t in targets:
                name = _terminal(t)
                if name:
                    self.lock_ctors.setdefault(("", name), kind)

    def _collect_attrs(self, cls: ast.ClassDef) -> None:
        """Pin ``self.attr`` types from ctor assignments (``self.x =
        Ctor(...)``) and from annotated ``__init__`` params passed
        straight through (``self.qos = qos`` with ``qos: QosGovernor``)."""
        for sub in cls.body:
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ann: dict[str, str] = {}
            for a in (list(sub.args.posonlyargs) + list(sub.args.args)
                      + list(sub.args.kwonlyargs)):
                names = _ann_names(a.annotation)
                if names:
                    ann[a.arg] = names[0]
            for node in _walk_scope(sub.body):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                if isinstance(node.value, ast.Call):
                    ctor = _terminal(node.value.func)
                    if ctor and ctor in _LOCK_CTORS:
                        self.lock_ctors[(cls.name, tgt.attr)] = \
                            _LOCK_CTORS[ctor]
                        self.lock_ctors.setdefault(
                            ("", tgt.attr), _LOCK_CTORS[ctor])
                    elif ctor and ctor[:1].isupper():
                        self.attr_types[(cls.name, tgt.attr)] = ctor
                elif isinstance(node.value, ast.Name) \
                        and node.value.id in ann:
                    self.attr_types[(cls.name, tgt.attr)] = \
                        ann[node.value.id]

    def lock_kind(self, owner: str, name: str) -> str | None:
        """'lock'|'cond'|'event' for ctor-pinned names, class scope
        first; None when there is no ctor evidence."""
        if owner and (owner, name) in self.lock_ctors:
            return self.lock_ctors[(owner, name)]
        return self.lock_ctors.get(("", name))


# ---------------------------------------------------------------------------
# the package index
# ---------------------------------------------------------------------------

def package_root_for(path: str) -> str | None:
    """Topmost ancestor directory of ``path`` that is a package (has
    ``__init__.py`` all the way down). None for standalone modules."""
    d = os.path.dirname(os.path.abspath(path))
    if not os.path.exists(os.path.join(d, "__init__.py")):
        return None
    while True:
        parent = os.path.dirname(d)
        if parent == d \
                or not os.path.exists(os.path.join(parent, "__init__.py")):
            return d
        d = parent


class PackageIndex:
    """Pass 1: every module under one package root, parsed and
    cross-resolved, with per-function summaries at fixpoint."""

    def __init__(self, pkg_dir: str):
        self.pkg_dir = os.path.abspath(pkg_dir)
        self.top = os.path.basename(self.pkg_dir)
        self.modules: dict[str, ModuleIndex] = {}
        self.by_path: dict[str, ModuleIndex] = {}
        self.funcs: dict[FuncKey, FuncInfo] = {}
        self.summaries: dict[FuncKey, Summary] = {}
        self._build()

    @classmethod
    def solo(cls, path: str, src: str, tree: ast.Module) -> "PackageIndex":
        """A one-module index for standalone files (and ``lint_source``
        fixtures): same API, nothing cross-module resolves — analysis
        degrades exactly to the v1 module-local behavior."""
        idx = object.__new__(cls)
        idx.pkg_dir = os.path.dirname(os.path.abspath(path))
        idx.top = ""
        idx.modules = {}
        idx.by_path = {}
        idx.funcs = {}
        idx.summaries = {}
        stem = os.path.splitext(os.path.basename(path))[0]
        mi = ModuleIndex(os.path.abspath(path), stem, src, tree,
                         False, "")
        idx.modules[stem] = mi
        idx.by_path[os.path.abspath(path)] = mi
        idx._resolve_imports(mi)
        idx._collect_funcs(mi)
        for info in idx.funcs.values():
            idx._collect_edges(mi, info)
        idx._fixpoint()
        return idx

    # -- construction -----------------------------------------------------

    def _build(self) -> None:
        for dirpath, dirs, files in os.walk(self.pkg_dir):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for name in sorted(files):
                if name.endswith(".py"):
                    self._add_module(os.path.join(dirpath, name))
        for mi in self.modules.values():
            self._resolve_imports(mi)
        for mi in self.modules.values():
            self._collect_funcs(mi)
        for info in self.funcs.values():
            mi = self.modules[info.key[0]]
            self._collect_edges(mi, info)
        self._fixpoint()

    def _add_module(self, path: str) -> None:
        rel = os.path.relpath(path, os.path.dirname(self.pkg_dir))
        parts = rel[:-3].split(os.sep)
        is_pkg = parts[-1] == "__init__"
        if is_pkg:
            parts = parts[:-1]
        modname = ".".join(parts)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            return
        mi = ModuleIndex(path, modname, src, tree, is_pkg, self.top)
        self.modules[modname] = mi
        self.by_path[os.path.abspath(path)] = mi

    def _resolve_imports(self, mi: ModuleIndex) -> None:
        parts = mi.modname.split(".")
        # the anchor package relative imports resolve against
        base = parts if mi.is_pkg else parts[:-1]
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level > 0:
                    anchor = base[:len(base) - (node.level - 1)]
                else:
                    anchor = []
                target = anchor + (node.module.split(".")
                                   if node.module else [])
                tmod = ".".join(target)
                for alias in node.names:
                    local = alias.asname or alias.name
                    full = f"{tmod}.{alias.name}" if tmod else alias.name
                    if full in self.modules:
                        mi.imports[local] = ("mod", full)
                    elif tmod in self.modules:
                        mi.imports[local] = ("sym", tmod, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name not in self.modules:
                        continue
                    if alias.asname:
                        mi.imports[alias.asname] = ("mod", alias.name)
                    else:
                        mi.dotted_mods.add(alias.name)

    def _collect_funcs(self, mi: ModuleIndex) -> None:
        for (cls, name), node in mi.defs.items():
            key = (mi.modname, cls, name)
            info = FuncInfo(key, node,
                            isinstance(node, ast.AsyncFunctionDef))
            self.funcs[key] = info
            self.summaries[key] = self._direct_summary(mi, cls, info)

    # -- resolution -------------------------------------------------------

    def _class_key(self, modname: str, name: str,
                   _depth: int = 0) -> tuple[str, str] | None:
        """(module, Class) for a class named ``name`` visible in
        ``modname`` — local class or one import hop."""
        mi = self.modules.get(modname)
        if mi is None or _depth > 2:
            return None
        if name in mi.classes:
            return (modname, name)
        b = mi.imports.get(name)
        if b and b[0] == "sym":
            return self._class_key(b[1], b[2], _depth + 1)
        return None

    def resolve_call(self, mi: ModuleIndex, owner: str,
                     call: ast.Call) -> FuncKey | None:
        """FuncKey of the function this call lands in, or None when the
        heuristic can't tell (which keeps v1 behavior: unresolved calls
        are simply not analyzed)."""
        f = call.func
        if isinstance(f, ast.Name):
            if ("", f.id) in mi.defs:
                return (mi.modname, "", f.id)
            b = mi.imports.get(f.id)
            if b and b[0] == "sym":
                key = (b[1], "", b[2])
                if key in self.funcs:
                    return key
            return None
        if not isinstance(f, ast.Attribute):
            return None
        meth = f.attr
        recv = f.value
        if isinstance(recv, ast.Name):
            rid = recv.id
            if rid in ("self", "cls") and owner:
                key = (mi.modname, owner, meth)
                return key if key in self.funcs else None
            b = mi.imports.get(rid)
            if b is not None:
                if b[0] == "mod":
                    key = (b[1], "", meth)
                    return key if key in self.funcs else None
                ck = self._class_key(b[1], b[2]) \
                    if b[2] in self.modules.get(b[1],
                                                mi).classes else None
                if ck is None:
                    # imported module-level singleton (POOL, REGISTRY…)
                    smi = self.modules.get(b[1])
                    ctor = smi.instances.get(b[2]) if smi else None
                    ck = self._class_key(b[1], ctor) if ctor else None
                if ck:
                    key = (ck[0], ck[1], meth)
                    return key if key in self.funcs else None
                return None
            if rid in mi.classes:
                key = (mi.modname, rid, meth)
                return key if key in self.funcs else None
            ctor = mi.instances.get(rid)
            if ctor:
                ck = self._class_key(mi.modname, ctor)
                if ck:
                    key = (ck[0], ck[1], meth)
                    return key if key in self.funcs else None
            return None
        # self.attr.method() with a pinned attr type
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id in ("self", "cls") and owner:
            tname = mi.attr_types.get((owner, recv.attr))
            if tname:
                ck = self._class_key(mi.modname, tname)
                if ck:
                    key = (ck[0], ck[1], meth)
                    return key if key in self.funcs else None
            return None
        # fully dotted module chain (plain `import a.b.c` style)
        d = _dotted(f)
        if d:
            modpath, _, fname = d.rpartition(".")
            if modpath in self.modules:
                key = (modpath, "", fname)
                if key in self.funcs:
                    return key
        return None

    def lock_identity(self, mi: ModuleIndex, owner: str,
                      expr: ast.expr) -> tuple[str, str] | None:
        """(identity, kind) for an ``async with`` context expression that
        is an asyncio lock/condition/semaphore; identity is stable across
        modules (mod.Class.attr) so the package-wide ordering graph can
        join edges taken in different files."""
        target = expr
        if isinstance(target, ast.Call):
            target = target.func
        name = _terminal(target)
        if name is None:
            return None
        # an imported lock belongs to its DEFINING module — both sides
        # of a cross-module cycle must agree on the identity or the
        # ordering graph never joins the edges
        if isinstance(target, ast.Name):
            b = mi.imports.get(target.id)
            if b is not None and b[0] == "sym":
                smi = self.modules.get(b[1])
                if smi is not None:
                    skind = smi.lock_kind("", b[2])
                    if skind == "event":
                        return None
                    if skind is None and not _LOCKISH_RE.search(b[2]):
                        return None
                    return (f"{smi.disp}.{b[2]}", skind or "lock")
        kind = mi.lock_kind(owner, name)
        if kind == "event":
            return None
        if kind is None and not _LOCKISH_RE.search(name):
            return None
        kind = kind or "lock"
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id in ("self", "cls") and owner:
            return (f"{mi.disp}.{owner}.{name}", kind)
        return (f"{mi.disp}.{name}", kind)

    # -- summaries --------------------------------------------------------

    def _direct_summary(self, mi: ModuleIndex, owner: str,
                        info: FuncInfo) -> Summary:
        s = Summary()
        body = info.node.body
        for call, reason in _scan_blocking(body):
            if ("DF001", call.lineno) in mi.suppressed:
                # a reasoned definition-site suppression retires the
                # hazard package-wide, not just in this module
                mi.summary_used.add(("DF001", call.lineno))
                continue
            s.blocking = (reason, "")
            break
        if info.is_async:
            lk = lambda name: mi.lock_kind(owner, name)  # noqa: E731
            for node in _walk_scope(body):
                if isinstance(node, ast.Await):
                    park = _park_reason(node.value, lk)
                    if park is not None:
                        if ("DF009", node.lineno) in mi.suppressed:
                            mi.summary_used.add(("DF009", node.lineno))
                        elif s.parks is None:
                            s.parks = (park, "")
                        continue
                    if (isinstance(node.value, ast.Call)
                            and _terminal(node.value.func)
                            in _SLOW_AWAITS):
                        if ("DF005", node.lineno) in mi.suppressed:
                            mi.summary_used.add(("DF005", node.lineno))
                        elif s.slow is None:
                            t = _terminal(node.value.func)
                            s.slow = (f"awaits {t}(…)", "")
                elif isinstance(node, ast.AsyncWith):
                    for item in node.items:
                        li = self.lock_identity(mi, owner,
                                                item.context_expr)
                        if li is not None:
                            s.acquires.setdefault(li[0], "")
        return s

    def _collect_edges(self, mi: ModuleIndex, info: FuncInfo) -> None:
        owner = info.key[1]
        for node in _walk_scope(info.node.body):
            if isinstance(node, ast.Await) \
                    and isinstance(node.value, ast.Call):
                key = self.resolve_call(mi, owner, node.value)
                if key is not None and key != info.key:
                    info.edges.append(("await", key, node.lineno))
            elif isinstance(node, ast.Call):
                key = self.resolve_call(mi, owner, node)
                if key is not None and key != info.key:
                    info.edges.append(("call", key, node.lineno))

    def _fixpoint(self) -> None:
        """Propagate summaries over resolved call edges until stable.
        Monotone lattice (facts only appear), so this terminates; the
        package's call graph converges in a handful of rounds."""
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for key, info in self.funcs.items():
                s = self.summaries[key]
                for kind, callee, _line in info.edges:
                    cs = self.summaries.get(callee)
                    ci = self.funcs.get(callee)
                    if cs is None or ci is None:
                        continue
                    via = display(callee, self.top)
                    if (not ci.is_async and cs.blocking is not None
                            and s.blocking is None):
                        s.blocking = (cs.blocking[0], via)
                        changed = True
                    if kind == "await" and ci.is_async:
                        if cs.slow is not None and s.slow is None:
                            s.slow = (cs.slow[0], via)
                            changed = True
                        if cs.parks is not None and s.parks is None:
                            s.parks = (cs.parks[0], via)
                            changed = True
                        for lock in cs.acquires:
                            if lock not in s.acquires:
                                s.acquires[lock] = via
                                changed = True

    # -- interfaces for the cache ----------------------------------------

    def interface_digest(self, modname: str) -> str:
        """Digest of everything a *caller* of this module can observe
        through the analysis: exported def/class names, asyncness,
        fixpoint summaries, module-level singletons, and import bindings
        (rebinding a re-exported ``POOL`` to another class changes what
        a caller's call sites resolve to). A dependency edit that
        doesn't move any of this cannot change a dependent's findings —
        the cache key the tier-1 gate's speed rides on. Memoized per
        index (summaries are frozen once the fixpoint ran)."""
        memo = self.__dict__.setdefault("_iface_memo", {})
        if modname in memo:
            return memo[modname]
        mi = self.modules.get(modname)
        if mi is None:
            memo[modname] = "absent"
            return "absent"
        items: list = []
        for (cls, name), _node in sorted(mi.defs.items()):
            key = (modname, cls, name)
            info = self.funcs.get(key)
            summ = self.summaries.get(key)
            items.append((cls, name, bool(info and info.is_async),
                          summ.digest_parts() if summ else ()))
        items.append(tuple(sorted(mi.instances.items())))
        items.append(tuple(sorted((k, v) for k, v in mi.imports.items())))
        digest = hashlib.sha256(repr(items).encode()).hexdigest()
        memo[modname] = digest
        return digest

    def _dep_closure(self, mi: ModuleIndex) -> set[str]:
        """TRANSITIVE in-package imports: call resolution can hop
        through a re-exporting module (``from .b import POOL`` where b
        built POOL from impl's class), so a dependent's key must cover
        the modules its call sites can land in, not just the ones it
        names. Memoized: summaries are frozen once the fixpoint ran."""
        memo = self.__dict__.setdefault("_closure_memo", {})
        if mi.modname in memo:
            return memo[mi.modname]
        seen: set[str] = set()
        stack = list({b[1] for b in mi.imports.values()}
                     | set(mi.dotted_mods))
        while stack:
            dep = stack.pop()
            if dep in seen:
                continue
            seen.add(dep)
            dmi = self.modules.get(dep)
            if dmi is None:
                continue
            stack.extend({b[1] for b in dmi.imports.values()}
                         | set(dmi.dotted_mods))
        memo[mi.modname] = seen
        return seen

    def import_surface_digest(self, mi: ModuleIndex) -> str:
        """Combined interface digest of every module ``mi`` can reach
        through imports — with the module's own content hash, the cache
        key."""
        h = hashlib.sha256()
        for dep in sorted(self._dep_closure(mi)):
            h.update(dep.encode())
            h.update(self.interface_digest(dep).encode())
        return h.hexdigest()
