"""DF009: package-wide async lock-ordering.

The only rule family that *cannot* run per module: a lock-order cycle is
two call sites in two files each holding its own lock while reaching for
the other's. It registers as a GlobalRule — the engine runs it once per
package graph after the per-module pass, and its findings land in the
module each edge site lives in (so the suppression grammar and the DF000
unused-suppression audit apply unchanged).

Incident (PR 11): the first QoS cut awaited ``qos.admit()`` while still
holding the PeerTaskManager lock. Admission parks on a bounded brownout
queue for up to a deadline — so one bulk request under pressure held the
lock every critical-path conductor creation needs: a priority inversion
by lock, invisible to DF005 because ``admit`` looks nothing like a
network primitive and lives two modules away. The shipped fix moved
admission OUTSIDE the lock (see peertask_manager.get_or_create_conductor,
whose comment is this rule's docstring in the flesh).

Three shapes, all computed off the pass-1 summaries:

* **re-entry** — while holding lock L, a call path re-acquires L.
  asyncio locks are non-reentrant: the task deadlocks against itself,
  with zero log output (the PR 2 silence, one abstraction up).
* **cycle** — the lock-acquisition graph (edge L→M: some path acquires
  M while holding L) has a cycle: two tasks taking the locks in
  opposite orders deadlock under load, which is precisely when it
  finally happens.
* **inversion** — while holding a lock, awaiting something whose
  summary says it *parks on capacity* (an admission future, a
  condition, a semaphore/queue) — or, name-heuristic arm, awaiting an
  unresolvable ``*.admit(...)``. The lock's critical section then lasts
  a stranger's deadline.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator

from . import Finding, GlobalRule, register_global
from .symbols import (
    ModuleIndex, PackageIndex, _park_reason, _terminal, _walk_scope,
    _SLOW_AWAITS, display,
)


@dataclass
class _Edge:
    src: str            # lock identity held
    dst: str            # lock identity acquired under it
    modname: str
    rel_line: int
    via: str            # callee display when the acquire is transitive


@register_global
class LockOrdering(GlobalRule):
    """DF009: async lock-ordering — cycles, re-entry, and the
    await-admission-while-holding-a-lock priority inversion (PR 11).
    See the module docstring for the incident."""

    code = "DF009"
    name = "async-lock-ordering"

    def check_package(self, index: PackageIndex,
                      analyzed: dict[str, str]) -> Iterator[Finding]:
        edges: list[_Edge] = []
        inversions: list[tuple[str, int, str, str]] = []  # mod, line, msg…
        for key, info in index.funcs.items():
            mi = index.modules.get(key[0])
            if mi is None:
                continue
            self._scan_fn(index, mi, key[1], info, edges, inversions)

        # ---- cycles over the package-wide graph -------------------------
        adj: dict[str, set[str]] = {}
        first_site: dict[tuple[str, str], _Edge] = {}
        for e in edges:
            adj.setdefault(e.src, set()).add(e.dst)
            first_site.setdefault((e.src, e.dst), e)

        reach_memo: dict[str, set[str]] = {}

        def reach(start: str) -> set[str]:
            if start in reach_memo:
                return reach_memo[start]
            seen: set[str] = set()
            stack = [start]
            while stack:
                n = stack.pop()
                for m in adj.get(n, ()):
                    if m not in seen:
                        seen.add(m)
                        stack.append(m)
            reach_memo[start] = seen
            return seen

        reported: set[tuple[str, int, str, str]] = set()
        for e in edges:
            if e.modname not in analyzed:
                continue
            rel = analyzed[e.modname]
            dedupe = (e.modname, e.rel_line, e.src, e.dst)
            if dedupe in reported:
                continue
            via = f" (via {e.via})" if e.via else ""
            if e.src == e.dst:
                reported.add(dedupe)
                yield Finding(
                    self.code, rel, e.rel_line, 0,
                    f"{e.src} re-acquired{via} while already held — "
                    f"asyncio locks are non-reentrant, so this task "
                    f"deadlocks against itself with zero log output")
            elif e.src in reach(e.dst):
                back = first_site.get((e.dst, e.src))
                where = ""
                if back is not None:
                    back_mod = index.modules.get(back.modname)
                    back_rel = analyzed.get(
                        back.modname,
                        back_mod.disp if back_mod else back.modname)
                    where = f" (reverse order at {back_rel}:" \
                            f"{back.rel_line})"
                reported.add(dedupe)
                yield Finding(
                    self.code, rel, e.rel_line, 0,
                    f"lock-order cycle: {e.dst} acquired{via} while "
                    f"holding {e.src}, but another path takes them in "
                    f"the opposite order{where} — two tasks interleaving "
                    f"there deadlock the pod")

        for modname, line, lockname, msg in inversions:
            if modname not in analyzed:
                continue
            yield Finding(self.code, analyzed[modname], line, 0,
                          f"priority inversion: {msg} while holding "
                          f"{lockname} — the critical section now lasts "
                          f"a stranger's admission deadline; take "
                          f"admission OUTSIDE the lock (PR 11 ptm shape)")

    # ------------------------------------------------------------------

    def _scan_fn(self, index: PackageIndex, mi: ModuleIndex, owner: str,
                 info, edges: list[_Edge],
                 inversions: list[tuple[str, int, str, str]]) -> None:
        for node in _walk_scope(info.node.body):
            if not isinstance(node, ast.AsyncWith):
                continue
            held: list[tuple[str, str]] = []    # (identity, local name)
            for item in node.items:
                li = index.lock_identity(mi, owner, item.context_expr)
                if li is not None:
                    name = _terminal(item.context_expr) or ""
                    held.append((li[0], name))
            if not held:
                continue
            held_ids = {h[0] for h in held}
            held_names = {h[1] for h in held}
            for sub in _walk_scope(node.body):
                if isinstance(sub, ast.AsyncWith):
                    for item in sub.items:
                        li = index.lock_identity(mi, owner,
                                                 item.context_expr)
                        if li is None:
                            continue
                        for hid in held_ids:
                            edges.append(_Edge(hid, li[0], mi.modname,
                                               sub.lineno, ""))
                elif isinstance(sub, ast.Await):
                    self._scan_await(index, mi, owner, sub, held,
                                     held_ids, held_names, edges,
                                     inversions)

    def _scan_await(self, index, mi, owner, sub: ast.Await, held,
                    held_ids, held_names, edges, inversions) -> None:
        awaited = sub.value
        # bare future awaited under a lock: parks on capacity DF005's
        # call-shaped heuristics can't see
        if isinstance(awaited, ast.Name):
            park = _park_reason(awaited,
                                lambda n: mi.lock_kind(owner, n))
            if park is not None:
                for hid in held_ids:
                    inversions.append((mi.modname, sub.lineno, hid, park))
            return
        if not isinstance(awaited, ast.Call):
            return
        recv = None
        if isinstance(awaited.func, ast.Attribute):
            recv = _terminal(awaited.func.value)
        if recv is not None and recv in held_names:
            return      # the held cond's own wait/wait_for: the pattern
        key = index.resolve_call(mi, owner, awaited)
        if key is None:
            # unresolved but directly park-shaped: `await sem.acquire()`
            # under a lock is the PR 11 inversion with no helper to
            # resolve through. Names DF005 already flags (wait_for,
            # queue get/put) stay DF005's — this arm takes only the
            # park-shapes DF005's vocabulary can't see.
            t = _terminal(awaited.func)
            if t not in _SLOW_AWAITS:
                park = _park_reason(awaited,
                                    lambda n: mi.lock_kind(owner, n))
                if park is not None:
                    # an explicit lock/sem acquire also feeds the
                    # ordering graph, same as its `async with` form
                    if t == "acquire":
                        li = index.lock_identity(mi, owner,
                                                 awaited.func.value)
                        if li is not None:
                            for hid in held_ids:
                                edges.append(_Edge(hid, li[0],
                                                   mi.modname,
                                                   sub.lineno, ""))
                    for hid in held_ids:
                        inversions.append(
                            (mi.modname, sub.lineno, hid, park))
            if t == "admit":
                target = f"{recv}.admit" if recv else "admit"
                for hid in held_ids:
                    inversions.append(
                        (mi.modname, sub.lineno, hid,
                         f"await {target}(…) — admission gates park on "
                         f"queue capacity"))
            return
        if key is not None:
            summ = index.summaries.get(key)
            info = index.funcs.get(key)
            if summ is None or info is None or not info.is_async:
                return
            callee = display(key, index.top)
            for lock in summ.acquires:
                via = summ.acquires[lock]
                hop = f"{callee} via {via}" if via else callee
                for hid in held_ids:
                    edges.append(_Edge(hid, lock, mi.modname,
                                       sub.lineno, hop))
            if summ.parks is not None:
                reason, via = summ.parks
                hop = f" (via {via})" if via else ""
                for hid in held_ids:
                    inversions.append(
                        (mi.modname, sub.lineno, hid,
                         f"await {callee}(…){hop} — it {reason}"))
