"""DF001–DF005: the asyncio hazard classes this fabric has actually hit.

Every rule here is a post-mortem made executable. The daemon runs ONE
event loop; these are the five ways this codebase has managed to wedge,
starve, or silently poison it across PRs 1–5.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import Finding, ModuleCtx, Rule, register

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a pure Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> str | None:
    """The last segment of a call target: `x` for x(), `m` for a.b.m()."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _walk_scope(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function scopes.

    A nested sync ``def`` or ``lambda`` inside a coroutine is (in this
    codebase) almost always an executor thunk or a callback — its body
    does not run on the event loop in the coroutine's context, so
    blocking calls there are exactly the *fix* for DF001, not the bug.
    Nested ``async def``s are separate coroutines and are visited in
    their own right by the rules' outer loops.
    """
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue    # a def seeded directly from `body` stays opaque too
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                continue
            stack.append(child)


def _lock_ctor_map(tree: ast.Module) -> dict[str, str]:
    """terminal-name -> 'cond' | 'event' | 'lock' for every assignment
    like ``self._cond = asyncio.Condition()`` anywhere in the module."""
    kinds = {"Condition": "cond", "Event": "event", "Lock": "lock",
             "Semaphore": "lock", "BoundedSemaphore": "lock"}
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        ctor = _terminal(value.func)
        kind = kinds.get(ctor or "")
        if kind is None:
            continue
        for t in targets:
            name = _terminal(t)
            if name:
                out[name] = kind
    return out


def _async_display(fn: ast.AsyncFunctionDef, owner: str | None) -> str:
    return f"{owner}.{fn.name}" if owner else fn.name


def _module_functions(tree: ast.Module):
    """(key -> sync def node, list of (async def node, owner-class-name)).

    Keys are ('', name) for module-level defs and (class, name) for
    methods — enough resolution to follow ``self.helper()`` and bare
    ``helper()`` call edges without a real type checker.
    """
    sync: dict[tuple[str, str], ast.FunctionDef] = {}
    asyncs: list[tuple[ast.AsyncFunctionDef, str | None]] = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            sync[("", node.name)] = node
        elif isinstance(node, ast.AsyncFunctionDef):
            asyncs.append((node, None))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    sync[(node.name, sub.name)] = sub
                elif isinstance(sub, ast.AsyncFunctionDef):
                    asyncs.append((sub, node.name))
    # a NESTED async def (a coroutine/async generator defined inside
    # another function, like file_client's `chunks()`) still runs on the
    # event loop — it must be a DF001 scan root too, or blocking IO can
    # hide one indentation level down
    top = {id(fn) for fn, _ in asyncs}
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef) and id(node) not in top:
            asyncs.append((node, None))
    return sync, asyncs


def _call_edges(fn, owner: str | None) -> Iterator[tuple[str, str]]:
    """Keys of module-local functions this function calls directly."""
    for node in _walk_scope(fn.body):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            yield ("", f.id)
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name)
              and f.value.id in ("self", "cls") and owner):
            yield (owner, f.attr)


# ---------------------------------------------------------------------------
# DF001 — blocking call on the event loop
# ---------------------------------------------------------------------------

_OS_IO = frozenset({
    "stat", "lstat", "listdir", "scandir", "walk", "remove", "unlink",
    "rename", "replace", "makedirs", "mkdir", "rmdir", "removedirs",
    "fsync", "ftruncate", "truncate", "utime", "link", "symlink",
    "chmod", "chown", "statvfs", "system", "popen",
})
_OSPATH_IO = frozenset({
    "getsize", "getmtime", "getctime", "exists", "isfile", "isdir",
    "islink", "samefile", "realpath",
})
_SHUTIL_IO = frozenset({
    "rmtree", "copy", "copy2", "copyfile", "copyfileobj", "copytree",
    "move", "disk_usage", "which",
})
_SOCKET_IO = frozenset({
    "getaddrinfo", "gethostbyname", "gethostbyaddr", "create_connection",
    "getfqdn",
})
_PATHLIB_IO = frozenset({
    "read_bytes", "read_text", "write_bytes", "write_text",
})
_DIGEST_HELPERS = frozenset({"hash_bytes", "hash_file"})
_FILE_METHODS = frozenset({"read", "write", "readline", "readlines",
                           "writelines"})


def _blocking_reason(call: ast.Call) -> str | None:
    d = _dotted(call.func)
    t = _terminal(call.func)
    if d in ("open", "io.open"):
        return "blocking open() — route file IO through an executor"
    if d == "time.sleep":
        return "time.sleep() parks the whole event loop — use asyncio.sleep"
    if d is not None:
        head, _, rest = d.partition(".")
        if head == "subprocess":
            return f"subprocess.{rest or d} blocks the loop — use " \
                   f"asyncio.create_subprocess_*"
        if head == "os" and rest in _OS_IO:
            return f"os.{rest} does synchronous IO on the loop thread"
        if d.startswith("os.path.") and d[len("os.path."):] in _OSPATH_IO:
            return f"{d} stats the filesystem on the loop thread"
        if head == "shutil" and rest in _SHUTIL_IO:
            return f"shutil.{rest} does synchronous IO on the loop thread"
        if head == "socket" and rest in _SOCKET_IO:
            return f"socket.{rest} can block on DNS/connect — use the " \
                   f"loop's async equivalents"
        if head == "hashlib" and call.args:
            return "whole-buffer hashlib digest on the loop thread — " \
                   "hash off-loop (see storage write_span / PR 5)"
    if t in _DIGEST_HELPERS:
        return f"{t}() traverses the whole buffer on the loop thread"
    if t in _PATHLIB_IO:
        return f".{t}() does synchronous file IO on the loop thread"
    return None


def _scan_blocking(fn_body: list[ast.stmt]) -> Iterator[tuple[ast.Call, str]]:
    """Yield (call, reason) for blocking calls lexically in this scope,
    plus reads/writes on file handles and hasher updates bound here."""
    handles: set[str] = set()
    hashers: set[str] = set()
    for node in _walk_scope(fn_body):
        if isinstance(node, ast.With):
            for item in node.items:
                if (isinstance(item.context_expr, ast.Call)
                        and _dotted(item.context_expr.func)
                        in ("open", "io.open")
                        and isinstance(item.optional_vars, ast.Name)):
                    handles.add(item.optional_vars.id)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if d in ("open", "io.open"):
                    handles.add(tgt.id)
                elif d is not None and d.startswith("hashlib."):
                    hashers.add(tgt.id)
    for node in _walk_scope(fn_body):
        if not isinstance(node, ast.Call):
            continue
        reason = _blocking_reason(node)
        if reason is not None:
            yield node, reason
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
            if f.value.id in handles and f.attr in _FILE_METHODS:
                yield node, (f"{f.value.id}.{f.attr}() on a blocking file "
                             f"handle — route file IO through an executor")
            elif f.value.id in hashers and f.attr == "update":
                yield node, ("whole-buffer hasher.update on the loop "
                             "thread — hash off-loop (PR 5 zero-stall rule)")


@register
class BlockingInAsync(Rule):
    """DF001: blocking call reachable from a coroutine.

    Incident (PR 5, zero-stall data plane): per-byte CPU and synchronous
    IO on the single event loop capped wire p95 at 68.6 ms and loop lag
    at 139 ms; moving hashing/IO off-loop cut them to 7.2 ms / 1.6 ms.
    The loop thread is the daemon's scarcest resource — a blocking
    ``open()``/``read()``/``time.sleep()``/whole-buffer hash anywhere a
    coroutine can reach stalls EVERY task in the process. Fix: hop
    through ``loop.run_in_executor`` (default executor for cold/control
    paths; the 4-thread storage pool is reserved for span landing).
    The rule follows module-local call edges, so a sync helper called
    from a coroutine (e.g. ``announcer.host_with_stats``) is analyzed
    too; code inside nested sync ``def``s/lambdas is exempt because
    those are the executor thunks themselves.
    """

    code = "DF001"
    name = "blocking-call-in-coroutine"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        sync, asyncs = _module_functions(ctx.tree)
        # transitively mark sync defs reachable from any coroutine
        reached: dict[tuple[str, str], str] = {}
        frontier: list[tuple[tuple[str, str], str]] = []
        for fn, owner in asyncs:
            origin = _async_display(fn, owner)
            for key in _call_edges(fn, owner):
                if key in sync and key not in reached:
                    reached[key] = origin
                    frontier.append((key, origin))
        while frontier:
            key, origin = frontier.pop()
            node = sync[key]
            owner = key[0] or None
            for nxt in _call_edges(node, owner):
                if nxt in sync and nxt not in reached:
                    reached[nxt] = origin
                    frontier.append((nxt, origin))

        for fn, owner in asyncs:
            where = _async_display(fn, owner)
            for call, reason in _scan_blocking(fn.body):
                yield Finding(self.code, ctx.rel, call.lineno,
                              call.col_offset,
                              f"{reason} (in async def {where})")
        for key, origin in sorted(reached.items()):
            node = sync[key]
            where = f"{key[0]}.{key[1]}" if key[0] else key[1]
            for call, reason in _scan_blocking(node.body):
                yield Finding(self.code, ctx.rel, call.lineno,
                              call.col_offset,
                              f"{reason} (in {where}(), called from "
                              f"coroutine {origin})")


# ---------------------------------------------------------------------------
# DF002 — orphaned create_task
# ---------------------------------------------------------------------------

_TASKGROUP_NAMES = frozenset({"tg", "taskgroup", "task_group", "nursery"})


@register
class OrphanedCreateTask(Rule):
    """DF002: ``create_task`` whose result is dropped on the floor.

    Incident class: the event loop keeps only a WEAK reference to tasks;
    a fire-and-forget ``create_task`` can be garbage-collected mid-
    flight, and if it isn't, its exception is swallowed silently ("Task
    exception was never retrieved" at interpreter exit, long after the
    damage). Both rpc/balancer.py and scheduler_session.py grew
    ``_close_tasks`` retain-and-discard sets after channel-close tasks
    leaked exactly this way. Fix: retain the task (and drain it on
    close), await it, or attach a done-callback that logs the exception
    — then the rule sees the result captured and stays quiet.
    """

    code = "DF002"
    name = "orphaned-create-task"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            call: ast.Call | None = None
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
            elif (isinstance(node, ast.Assign)
                  and len(node.targets) == 1
                  and isinstance(node.targets[0], ast.Name)
                  and node.targets[0].id == "_"
                  and isinstance(node.value, ast.Call)):
                call = node.value
            if call is None or _terminal(call.func) != "create_task":
                continue
            recv = (call.func.value if isinstance(call.func, ast.Attribute)
                    else None)
            rname = (_terminal(recv) or "").lower() if recv is not None \
                else ""
            if rname in _TASKGROUP_NAMES:
                continue        # TaskGroup retains and joins its children
            yield Finding(
                self.code, ctx.rel, call.lineno, call.col_offset,
                "create_task result discarded — the loop holds only a "
                "weak ref, so the task can be GC'd mid-flight and its "
                "exception is silently swallowed; retain it (and drain "
                "on close), await it, or add a done-callback that logs")


# ---------------------------------------------------------------------------
# DF003 — wait_for around Condition.wait
# ---------------------------------------------------------------------------

_CONDISH_RE = re.compile(r"cond", re.IGNORECASE)


@register
class WaitForOnConditionWait(Rule):
    """DF003: ``asyncio.wait_for(<cond>.wait(), t)`` — the PR 2 shape.

    Incident (PR 2, silent pod deadlock, zero log output):
    ``wait_for(self._cond.wait(), t)`` under the caller's ``async with``
    splits the lock scope and the wait across TWO tasks. A worker
    cancelled while parked there orphans the inner ``Condition.wait``,
    which re-acquires the condition lock in its ``finally`` and dies
    HOLDING it — every later acquirer (close(), add_parent, the
    teardown gather) queues on the poisoned lock forever. Fix: an
    atomic acquire+wait helper so the lock scope and the wait live in
    ONE coroutine (see ``piece_dispatcher._notified``), then
    ``wait_for`` that helper. ``Event.wait`` has no lock and is exempt
    when the receiver is a known ``asyncio.Event``.
    """

    code = "DF003"
    name = "wait-for-on-condition-wait"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        ctors = _lock_ctor_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal(node.func) != "wait_for" or not node.args:
                continue
            inner = node.args[0]
            if not (isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "wait"):
                continue
            rname = _terminal(inner.func.value) or ""
            kind = ctors.get(rname)
            if kind == "event":
                continue
            if kind == "cond" or (kind is None and _CONDISH_RE.search(rname)):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"wait_for({rname}.wait(), …) on a Condition splits "
                    f"the lock scope and the wait across two tasks — a "
                    f"cancellation leaves the condition lock held forever "
                    f"(PR 2 silent deadlock); use an atomic acquire+wait "
                    f"helper and wait_for THAT")


# ---------------------------------------------------------------------------
# DF004 — cancellation-swallowing except in a coroutine
# ---------------------------------------------------------------------------

def _type_names(expr: ast.expr | None) -> set[str]:
    if expr is None:
        return {"<bare>"}
    if isinstance(expr, ast.Tuple):
        return {t for e in expr.elts for t in _type_names(e)}
    t = _terminal(expr)
    return {t} if t else set()


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in _walk_scope(handler.body):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


@register
class BroadExceptInCoroutine(Rule):
    """DF004: bare/``BaseException`` except in a coroutine without
    re-raise — it eats ``CancelledError``.

    Incident (PR 1, seed-inherited stall): ``CancelledError`` is a
    ``BaseException`` precisely so ``except Exception`` misses it; a
    broad handler that doesn't re-raise turns a cancellation into a
    normal code path, leaving an undead coroutine its owner believes is
    gone — the e2e suites timed out on exactly such an orphan. A
    handler is clean if it contains a bare ``raise``, or if an earlier
    ``except CancelledError`` arm of the same ``try`` already re-raised.
    ``except Exception`` is always fine.
    """

    code = "DF004"
    name = "cancellation-swallowing-except"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_scope(fn.body):
                if not isinstance(node, ast.Try):
                    continue
                cancelled_handled = False
                for handler in node.handlers:
                    names = _type_names(handler.type)
                    if "CancelledError" in names and _reraises(handler):
                        cancelled_handled = True
                        continue
                    if not names & {"<bare>", "BaseException"}:
                        continue
                    if cancelled_handled or _reraises(handler):
                        continue
                    what = "bare except" if "<bare>" in names \
                        else "except BaseException"
                    yield Finding(
                        self.code, ctx.rel, handler.lineno,
                        handler.col_offset,
                        f"{what} in async def {fn.name} swallows "
                        f"CancelledError — re-raise it (bare `raise`, or "
                        f"an `except asyncio.CancelledError: raise` arm "
                        f"first), or narrow to `except Exception`")


# ---------------------------------------------------------------------------
# DF005 — slow await while holding an async lock
# ---------------------------------------------------------------------------

_LOCKISH_RE = re.compile(r"lock|cond|sem|mutex", re.IGNORECASE)
_SLOW_AWAITS = frozenset({
    "sleep", "gather", "wait", "wait_for", "open_connection",
    "getaddrinfo", "connect", "request", "get", "post", "put", "patch",
    "delete", "fetch", "recv", "read", "readexactly", "readline",
    "readuntil", "drain", "send", "send_json", "json", "text",
})


@register
class SlowAwaitUnderLock(Rule):
    """DF005: awaiting network/sleep/queue primitives while holding an
    ``async with`` lock or condition.

    Incident class (PR 2 adjacent): the dispatcher deadlock taught us
    that anything parked inside a held condition outlives the caller's
    patience — and a network read or sleep under a lock converts one
    slow peer into a process-wide convoy (every other task queues on
    the lock for the duration of a stranger's RTT). Inside ``async with
    <lock>:`` the only await that belongs is the lock's own
    ``wait``/``wait_for``; compute the decision under the lock, do the
    IO outside it.
    """

    code = "DF005"
    name = "slow-await-under-lock"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        ctors = _lock_ctor_map(ctx.tree)

        def lockish(expr: ast.expr) -> str | None:
            name = _terminal(expr)
            if name is None and isinstance(expr, ast.Call):
                name = _terminal(expr.func)
            if name is None:
                return None
            kind = ctors.get(name)
            if kind in ("cond", "lock"):
                return name
            if kind is None and _LOCKISH_RE.search(name):
                return name
            return None

        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_scope(fn.body):
                if not isinstance(node, ast.AsyncWith):
                    continue
                held = {n for item in node.items
                        if (n := lockish(item.context_expr)) is not None}
                if not held:
                    continue
                for sub in _walk_scope(node.body):
                    if not (isinstance(sub, ast.Await)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    call = sub.value
                    fname = _terminal(call.func)
                    if fname not in _SLOW_AWAITS:
                        continue
                    recv = (call.func.value
                            if isinstance(call.func, ast.Attribute) else None)
                    if recv is not None and _terminal(recv) in held:
                        continue    # cond.wait()/.wait_for(): the pattern
                    yield Finding(
                        self.code, ctx.rel, sub.lineno, sub.col_offset,
                        f"await {fname}(…) while holding "
                        f"{'/'.join(sorted(held))} — a slow peer or timer "
                        f"convoys every other task on this lock; move the "
                        f"IO outside the lock scope (in async def "
                        f"{fn.name})")
