"""DF001–DF005: the asyncio hazard classes this fabric has actually hit.

Every rule here is a post-mortem made executable. The daemon runs ONE
event loop; these are the five ways this codebase has managed to wedge,
starve, or silently poison it across PRs 1–5.

v2: DF001 and DF005 are **interprocedural**. The module-local pass is
unchanged (and is all that runs for standalone files / fixtures), but
when the module belongs to an indexed package, call sites that resolve
across module boundaries are checked against the callee's fixpoint
summary — a blocking helper in ``common/`` called from a coroutine in
``daemon/`` is reported *at the call site*, which is where the executor
hop (the fix) belongs.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from . import Finding, ModuleCtx, Rule, register
from .symbols import (
    _blocking_reason, _dotted, _scan_blocking, _terminal, _walk_scope,
    _CONDISH_RE, _LOCKISH_RE, _SLOW_AWAITS, display,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _lock_ctor_map(tree: ast.Module) -> dict[str, str]:
    """terminal-name -> 'cond' | 'event' | 'lock' for every assignment
    like ``self._cond = asyncio.Condition()`` anywhere in the module."""
    kinds = {"Condition": "cond", "Event": "event", "Lock": "lock",
             "Semaphore": "lock", "BoundedSemaphore": "lock"}
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        ctor = _terminal(value.func)
        kind = kinds.get(ctor or "")
        if kind is None:
            continue
        for t in targets:
            name = _terminal(t)
            if name:
                out[name] = kind
    return out


def _async_display(fn: ast.AsyncFunctionDef, owner: str | None) -> str:
    return f"{owner}.{fn.name}" if owner else fn.name


def _module_functions(tree: ast.Module):
    """(key -> sync def node, list of (async def node, owner-class-name)).

    Keys are ('', name) for module-level defs and (class, name) for
    methods — enough resolution to follow ``self.helper()`` and bare
    ``helper()`` call edges without a real type checker.
    """
    sync: dict[tuple[str, str], ast.FunctionDef] = {}
    asyncs: list[tuple[ast.AsyncFunctionDef, str | None]] = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            sync[("", node.name)] = node
        elif isinstance(node, ast.AsyncFunctionDef):
            asyncs.append((node, None))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    sync[(node.name, sub.name)] = sub
                elif isinstance(sub, ast.AsyncFunctionDef):
                    asyncs.append((sub, node.name))
    # a NESTED async def (a coroutine/async generator defined inside
    # another function, like file_client's `chunks()`) still runs on the
    # event loop — it must be a DF001 scan root too, or blocking IO can
    # hide one indentation level down
    top = {id(fn) for fn, _ in asyncs}
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef) and id(node) not in top:
            asyncs.append((node, None))
    return sync, asyncs


def _call_edges(fn, owner: str | None) -> Iterator[tuple[str, str]]:
    """Keys of module-local functions this function calls directly."""
    for node in _walk_scope(fn.body):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            yield ("", f.id)
        elif (isinstance(f, ast.Attribute)
              and isinstance(f.value, ast.Name)
              and f.value.id in ("self", "cls") and owner):
            yield (owner, f.attr)


# ---------------------------------------------------------------------------
# DF001 — blocking call on the event loop
# ---------------------------------------------------------------------------

@register
class BlockingInAsync(Rule):
    """DF001: blocking call reachable from a coroutine.

    Incident (PR 5, zero-stall data plane): per-byte CPU and synchronous
    IO on the single event loop capped wire p95 at 68.6 ms and loop lag
    at 139 ms; moving hashing/IO off-loop cut them to 7.2 ms / 1.6 ms.
    The loop thread is the daemon's scarcest resource — a blocking
    ``open()``/``read()``/``time.sleep()``/whole-buffer hash anywhere a
    coroutine can reach stalls EVERY task in the process. Fix: hop
    through ``loop.run_in_executor`` (default executor for cold/control
    paths; the 4-thread storage pool is reserved for span landing).

    The rule follows call edges transitively — module-local ones as in
    v1, and (v2) edges that the package index resolves across module
    boundaries: a sync helper in ``common/`` whose summary says it
    blocks is reported at its call site in the coroutine's own module,
    because that call site is where the executor hop goes. Code inside
    nested sync ``def``s/lambdas is exempt because those are the
    executor thunks themselves.
    """

    code = "DF001"
    name = "blocking-call-in-coroutine"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        sync, asyncs = _module_functions(ctx.tree)
        # transitively mark sync defs reachable from any coroutine
        reached: dict[tuple[str, str], str] = {}
        frontier: list[tuple[tuple[str, str], str]] = []
        for fn, owner in asyncs:
            origin = _async_display(fn, owner)
            for key in _call_edges(fn, owner):
                if key in sync and key not in reached:
                    reached[key] = origin
                    frontier.append((key, origin))
        while frontier:
            key, origin = frontier.pop()
            node = sync[key]
            owner = key[0] or None
            for nxt in _call_edges(node, owner):
                if nxt in sync and nxt not in reached:
                    reached[nxt] = origin
                    frontier.append((nxt, origin))

        for fn, owner in asyncs:
            where = _async_display(fn, owner)
            for call, reason in _scan_blocking(fn.body):
                yield Finding(self.code, ctx.rel, call.lineno,
                              call.col_offset,
                              f"{reason} (in async def {where})")
            yield from self._cross_module(ctx, fn.body, owner or "",
                                          f"async def {where}")
        for key, origin in sorted(reached.items()):
            node = sync[key]
            where = f"{key[0]}.{key[1]}" if key[0] else key[1]
            for call, reason in _scan_blocking(node.body):
                yield Finding(self.code, ctx.rel, call.lineno,
                              call.col_offset,
                              f"{reason} (in {where}(), called from "
                              f"coroutine {origin})")
            yield from self._cross_module(
                ctx, node.body, key[0],
                f"{where}(), called from coroutine {origin}")

    def _cross_module(self, ctx: ModuleCtx, body: list[ast.stmt],
                      owner: str, where: str) -> Iterator[Finding]:
        """v2: calls in this (coroutine-reachable) scope that resolve to
        a *sync* function in another module whose summary blocks."""
        index, mi = ctx.index, ctx.mod
        if index is None or mi is None:
            return
        for node in _walk_scope(body):
            if not isinstance(node, ast.Call):
                continue
            if _blocking_reason(node) is not None:
                continue        # already flagged by the direct scan
            key = index.resolve_call(mi, owner, node)
            if key is None or key[0] == mi.modname:
                continue        # local edges are the v1 pass's job
            info = index.funcs.get(key)
            summ = index.summaries.get(key)
            if info is None or summ is None or info.is_async \
                    or summ.blocking is None:
                continue
            reason, via = summ.blocking
            callee = display(key, index.top)
            hop = f" (via {via})" if via else ""
            yield Finding(
                self.code, ctx.rel, node.lineno, node.col_offset,
                f"call into {callee}(){hop} runs blocking IO on the "
                f"loop thread: {reason} (in {where})")


# ---------------------------------------------------------------------------
# DF002 — orphaned create_task
# ---------------------------------------------------------------------------

_TASKGROUP_NAMES = frozenset({"tg", "taskgroup", "task_group", "nursery"})


@register
class OrphanedCreateTask(Rule):
    """DF002: ``create_task`` whose result is dropped on the floor.

    Incident class: the event loop keeps only a WEAK reference to tasks;
    a fire-and-forget ``create_task`` can be garbage-collected mid-
    flight, and if it isn't, its exception is swallowed silently ("Task
    exception was never retrieved" at interpreter exit, long after the
    damage). Both rpc/balancer.py and scheduler_session.py grew
    ``_close_tasks`` retain-and-discard sets after channel-close tasks
    leaked exactly this way. Fix: retain the task (and drain it on
    close), await it, or attach a done-callback that logs the exception
    — then the rule sees the result captured and stays quiet.
    """

    code = "DF002"
    name = "orphaned-create-task"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            call: ast.Call | None = None
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
            elif (isinstance(node, ast.Assign)
                  and len(node.targets) == 1
                  and isinstance(node.targets[0], ast.Name)
                  and node.targets[0].id == "_"
                  and isinstance(node.value, ast.Call)):
                call = node.value
            if call is None or _terminal(call.func) != "create_task":
                continue
            recv = (call.func.value if isinstance(call.func, ast.Attribute)
                    else None)
            rname = (_terminal(recv) or "").lower() if recv is not None \
                else ""
            if rname in _TASKGROUP_NAMES:
                continue        # TaskGroup retains and joins its children
            yield Finding(
                self.code, ctx.rel, call.lineno, call.col_offset,
                "create_task result discarded — the loop holds only a "
                "weak ref, so the task can be GC'd mid-flight and its "
                "exception is silently swallowed; retain it (and drain "
                "on close), await it, or add a done-callback that logs")


# ---------------------------------------------------------------------------
# DF003 — wait_for around Condition.wait
# ---------------------------------------------------------------------------

@register
class WaitForOnConditionWait(Rule):
    """DF003: ``asyncio.wait_for(<cond>.wait(), t)`` — the PR 2 shape.

    Incident (PR 2, silent pod deadlock, zero log output):
    ``wait_for(self._cond.wait(), t)`` under the caller's ``async with``
    splits the lock scope and the wait across TWO tasks. A worker
    cancelled while parked there orphans the inner ``Condition.wait``,
    which re-acquires the condition lock in its ``finally`` and dies
    HOLDING it — every later acquirer (close(), add_parent, the
    teardown gather) queues on the poisoned lock forever. Fix: an
    atomic acquire+wait helper so the lock scope and the wait live in
    ONE coroutine (see ``piece_dispatcher._notified``), then
    ``wait_for`` that helper. ``Event.wait`` has no lock and is exempt
    when the receiver is a known ``asyncio.Event``.
    """

    code = "DF003"
    name = "wait-for-on-condition-wait"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        ctors = _lock_ctor_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal(node.func) != "wait_for" or not node.args:
                continue
            inner = node.args[0]
            if not (isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "wait"):
                continue
            rname = _terminal(inner.func.value) or ""
            kind = ctors.get(rname)
            if kind == "event":
                continue
            if kind == "cond" or (kind is None and _CONDISH_RE.search(rname)):
                yield Finding(
                    self.code, ctx.rel, node.lineno, node.col_offset,
                    f"wait_for({rname}.wait(), …) on a Condition splits "
                    f"the lock scope and the wait across two tasks — a "
                    f"cancellation leaves the condition lock held forever "
                    f"(PR 2 silent deadlock); use an atomic acquire+wait "
                    f"helper and wait_for THAT")


# ---------------------------------------------------------------------------
# DF004 — cancellation-swallowing except in a coroutine
# ---------------------------------------------------------------------------

def _type_names(expr: ast.expr | None) -> set[str]:
    if expr is None:
        return {"<bare>"}
    if isinstance(expr, ast.Tuple):
        return {t for e in expr.elts for t in _type_names(e)}
    t = _terminal(expr)
    return {t} if t else set()


def _reraises(handler: ast.ExceptHandler) -> bool:
    for node in _walk_scope(handler.body):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


@register
class BroadExceptInCoroutine(Rule):
    """DF004: bare/``BaseException`` except in a coroutine without
    re-raise — it eats ``CancelledError``.

    Incident (PR 1, seed-inherited stall): ``CancelledError`` is a
    ``BaseException`` precisely so ``except Exception`` misses it; a
    broad handler that doesn't re-raise turns a cancellation into a
    normal code path, leaving an undead coroutine its owner believes is
    gone — the e2e suites timed out on exactly such an orphan. A
    handler is clean if it contains a bare ``raise``, or if an earlier
    ``except CancelledError`` arm of the same ``try`` already re-raised.
    ``except Exception`` is always fine.
    """

    code = "DF004"
    name = "cancellation-swallowing-except"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _walk_scope(fn.body):
                if not isinstance(node, ast.Try):
                    continue
                cancelled_handled = False
                for handler in node.handlers:
                    names = _type_names(handler.type)
                    if "CancelledError" in names and _reraises(handler):
                        cancelled_handled = True
                        continue
                    if not names & {"<bare>", "BaseException"}:
                        continue
                    if cancelled_handled or _reraises(handler):
                        continue
                    what = "bare except" if "<bare>" in names \
                        else "except BaseException"
                    yield Finding(
                        self.code, ctx.rel, handler.lineno,
                        handler.col_offset,
                        f"{what} in async def {fn.name} swallows "
                        f"CancelledError — re-raise it (bare `raise`, or "
                        f"an `except asyncio.CancelledError: raise` arm "
                        f"first), or narrow to `except Exception`")


# ---------------------------------------------------------------------------
# DF005 — slow await while holding an async lock
# ---------------------------------------------------------------------------

@register
class SlowAwaitUnderLock(Rule):
    """DF005: awaiting network/sleep/queue primitives while holding an
    ``async with`` lock or condition.

    Incident class (PR 2 adjacent): the dispatcher deadlock taught us
    that anything parked inside a held condition outlives the caller's
    patience — and a network read or sleep under a lock converts one
    slow peer into a process-wide convoy (every other task queues on
    the lock for the duration of a stranger's RTT). Inside ``async with
    <lock>:`` the only await that belongs is the lock's own
    ``wait``/``wait_for``; compute the decision under the lock, do the
    IO outside it.

    v2: besides the direct name heuristic, awaits whose call the package
    index resolves to a coroutine (any module) are checked against that
    coroutine's fixpoint summary — ``await self._flush()`` under a lock
    flags when ``_flush`` transitively awaits a network write three
    modules away.
    """

    code = "DF005"
    name = "slow-await-under-lock"

    def check(self, ctx: ModuleCtx) -> Iterator[Finding]:
        ctors = _lock_ctor_map(ctx.tree)
        index, mi = ctx.index, ctx.mod

        def lockish(expr: ast.expr) -> str | None:
            name = _terminal(expr)
            if name is None and isinstance(expr, ast.Call):
                name = _terminal(expr.func)
            if name is None:
                return None
            kind = ctors.get(name)
            if kind in ("cond", "lock"):
                return name
            if kind is None and _LOCKISH_RE.search(name):
                return name
            return None

        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            owner = ""
            if index is not None and mi is not None:
                for (cls, name), node in mi.defs.items():
                    if node is fn:
                        owner = cls
                        break
            for node in _walk_scope(fn.body):
                if not isinstance(node, ast.AsyncWith):
                    continue
                held = {n for item in node.items
                        if (n := lockish(item.context_expr)) is not None}
                if not held:
                    continue
                for sub in _walk_scope(node.body):
                    if not (isinstance(sub, ast.Await)
                            and isinstance(sub.value, ast.Call)):
                        continue
                    call = sub.value
                    fname = _terminal(call.func)
                    recv = (call.func.value
                            if isinstance(call.func, ast.Attribute) else None)
                    if recv is not None and _terminal(recv) in held:
                        continue    # cond.wait()/.wait_for(): the pattern
                    if fname in _SLOW_AWAITS:
                        yield Finding(
                            self.code, ctx.rel, sub.lineno, sub.col_offset,
                            f"await {fname}(…) while holding "
                            f"{'/'.join(sorted(held))} — a slow peer or "
                            f"timer convoys every other task on this "
                            f"lock; move the IO outside the lock scope "
                            f"(in async def {fn.name})")
                        continue
                    if index is None or mi is None:
                        continue
                    key = index.resolve_call(mi, owner, call)
                    if key is None:
                        continue
                    info = index.funcs.get(key)
                    summ = index.summaries.get(key)
                    if info is None or summ is None or not info.is_async \
                            or summ.slow is None:
                        continue
                    reason, via = summ.slow
                    callee = display(key, index.top)
                    hop = f" via {via}" if via else ""
                    yield Finding(
                        self.code, ctx.rel, sub.lineno, sub.col_offset,
                        f"await {callee}(…) while holding "
                        f"{'/'.join(sorted(held))} — it transitively "
                        f"{reason}{hop}, convoying every task on this "
                        f"lock; move the call outside the lock scope "
                        f"(in async def {fn.name})")
